"""Numerics flight recorder (telemetry/health + flight_recorder + the
trainer/optimizer wiring): config validation, in-graph probes (grouped grad
norms sharing the clipping reduction, finiteness flags, skip_update's bitwise
no-op), the host-side ring buffer + anomaly bundles, per-policy fault
injection through a real tiny-llama fit(), the healthy-path overhead contract
(AOT once, zero retraces, zero extra host syncs between boundaries), the hang
watchdog, and the tools/anomaly_report.py renderer — all tier-1 / CPU."""

import importlib.util
import json
import time
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from neuronx_distributed_training_tpu.optim.adamw import (
    AdamWConfig,
    adamw_update,
    global_norm,
    grouped_sq_norms,
    init_opt_state,
    opt_state_specs,
)
from neuronx_distributed_training_tpu.telemetry import (
    HealthConfig,
    HealthMonitor,
    HangWatchdog,
    TelemetryConfig,
    grad_group_of,
)
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


class TestHealthConfig:
    def test_defaults_disabled(self):
        hc = TelemetryConfig.from_config(None).health
        assert hc.enabled is False
        assert hc.policy == "dump_and_continue"
        assert hc.ring_buffer_steps == 32
        assert hc.watchdog_timeout_seconds == 0.0

    def test_bare_bool_enables(self):
        assert HealthConfig.from_config(True).enabled is True
        assert HealthConfig.from_config(False).enabled is False

    def test_unknown_key_rejected_at_load(self):
        from neuronx_distributed_training_tpu.config.loader import load_config

        cfg = {"exp_manager": {"telemetry": {"health": {"polcy": "halt"}}},
               "data": {"global_batch_size": 8, "micro_batch_size": 1}}
        with pytest.raises(ValueError, match="polcy"):
            load_config(cfg)

    def test_bad_policy_rejected_at_load(self):
        from neuronx_distributed_training_tpu.config.loader import load_config

        cfg = {"exp_manager": {"telemetry": {"health": {"policy": "ignore"}}},
               "data": {"global_batch_size": 8, "micro_batch_size": 1}}
        with pytest.raises(ValueError, match="halt"):
            load_config(cfg)

    def test_bad_values_rejected(self):
        with pytest.raises(ValueError, match="ring_buffer_steps"):
            HealthConfig.from_config({"ring_buffer_steps": 0})
        with pytest.raises(ValueError, match="watchdog_timeout_seconds"):
            HealthConfig.from_config({"watchdog_timeout_seconds": -1})
        with pytest.raises(ValueError, match="boolean"):
            HealthConfig.from_config({"enabled": "yes"})
        with pytest.raises(ValueError, match="max_bundles"):
            HealthConfig.from_config({"max_bundles": 0})

    def test_watchdog_without_any_monitor_rejected(self):
        # a watchdog that silently never arms is worse than a loud config
        # error — the dump path needs a bundle-capable monitor, which any
        # of health / fleet / control / a dump-action alert rule arms (the
        # cross-block check lives in TelemetryConfig, which sees them all)
        with pytest.raises(ValueError, match="bundle-capable"):
            TelemetryConfig.from_config({"health": {
                "enabled": False, "watchdog_timeout_seconds": 300}})
        # ...and each bundle-capable block legalizes it
        for block in ({"health": {"enabled": True,
                                  "watchdog_timeout_seconds": 300}},
                      {"health": {"watchdog_timeout_seconds": 300},
                       "fleet": {"enabled": True}},
                      {"health": {"watchdog_timeout_seconds": 300},
                       "control": {"enabled": True}},
                      {"health": {"watchdog_timeout_seconds": 300},
                       "alerts": [{"metric": "loss", "threshold": 1.0,
                                   "action": "dump"}]}):
            t = TelemetryConfig.from_config(block)
            assert t.health.watchdog_timeout_seconds == 300.0

    def test_blanket_telemetry_off_keeps_health_disabled(self):
        assert TelemetryConfig.from_config(False).health.enabled is False
        # blanket True switches the bool knobs but never silently opts into
        # the opt-state-changing health subtree
        assert TelemetryConfig.from_config(True).health.enabled is False

    def test_round_trip_through_loader(self):
        from neuronx_distributed_training_tpu.config.loader import load_config

        cfg = load_config({
            "exp_manager": {"telemetry": {"health": {
                "enabled": True, "policy": "skip_update",
                "ring_buffer_steps": 4, "watchdog_timeout_seconds": 9.0}}},
            "data": {"global_batch_size": 8, "micro_batch_size": 1},
        })
        hc = TelemetryConfig.from_config(
            cfg["exp_manager"]["telemetry"]).health
        assert hc.enabled and hc.policy == "skip_update"
        assert hc.ring_buffer_steps == 4
        assert hc.watchdog_timeout_seconds == 9.0


# ---------------------------------------------------------------------------
# grad grouping + grouped norms == clipping norm (one source of truth)
# ---------------------------------------------------------------------------


def _params():
    k = jax.random.PRNGKey(0)
    return {
        "embed": {"embedding": jax.random.normal(k, (16, 8))},
        "layers": {
            "attn": {"qkv": {"w": jax.random.normal(k, (2, 8, 8))}},
            "mlp": {"down": {"w": jax.random.normal(k, (2, 8, 8))}},
            "input_norm": {"scale": jnp.ones((2, 8))},
        },
        "final_norm": {"scale": jnp.ones((8,))},
    }


class TestGradGroups:
    def test_group_names(self):
        grads = _params()
        groups = grouped_sq_norms(grads, grad_group_of)
        assert set(groups) == {"embed", "layers/attn", "layers/mlp",
                               "layers/input_norm", "final_norm"}

    def test_grouped_sums_reproduce_global_norm(self):
        grads = _params()
        groups = grouped_sq_norms(grads, grad_group_of)
        np.testing.assert_allclose(
            float(jnp.sqrt(sum(groups.values()))), float(global_norm(grads)),
            rtol=1e-6)

    def test_adamw_reports_groups_and_identical_gnorm(self):
        params = _params()
        grads = jax.tree_util.tree_map(lambda p: 0.1 * p, params)
        opt = init_opt_state(params)
        _, _, plain = adamw_update(params, grads, opt, 1e-3, AdamWConfig())
        _, _, grouped = adamw_update(params, grads, opt, 1e-3, AdamWConfig(),
                                     grad_group_fn=grad_group_of)
        np.testing.assert_allclose(float(grouped["grad_norm"]),
                                   float(plain["grad_norm"]), rtol=1e-6)
        assert bool(grouped["updates_finite"])
        assert set(grouped["group_norms"]) == {
            "embed", "layers/attn", "layers/mlp", "layers/input_norm",
            "final_norm"}

    def test_grouped_update_matches_plain(self):
        # the health probes must not perturb the update itself
        params = _params()
        grads = jax.tree_util.tree_map(lambda p: 0.1 * p, params)
        opt = init_opt_state(params)
        p1, s1, _ = adamw_update(params, grads, opt, 1e-3, AdamWConfig())
        p2, s2, _ = adamw_update(params, grads, opt, 1e-3, AdamWConfig(),
                                 grad_group_fn=grad_group_of)
        for a, b in zip(jax.tree_util.tree_leaves((p1, s1)),
                        jax.tree_util.tree_leaves((p2, s2))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=0)


# ---------------------------------------------------------------------------
# skip_nonfinite: the in-graph update suppression
# ---------------------------------------------------------------------------


def _trees_bitwise_equal(a, b) -> bool:
    return bool(jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda x, y: bool(jnp.array_equal(x, y, equal_nan=True)), a, b)))


class TestSkipNonfinite:
    def test_nan_grads_freeze_everything(self):
        params = _params()
        grads = jax.tree_util.tree_map(lambda p: 0.1 * p, params)
        grads["layers"]["attn"]["qkv"]["w"] = (
            grads["layers"]["attn"]["qkv"]["w"].at[0, 0, 0].set(jnp.nan))
        opt = init_opt_state(params)
        new_p, new_s, m = adamw_update(params, grads, opt, 1e-3, AdamWConfig(),
                                       skip_nonfinite=True)
        assert not bool(m["updates_finite"])
        assert _trees_bitwise_equal(new_p, params)
        assert _trees_bitwise_equal(new_s, opt)  # incl. the step counter

    def test_finite_grads_update_exactly_as_without_skip(self):
        params = _params()
        grads = jax.tree_util.tree_map(lambda p: 0.1 * p, params)
        opt = init_opt_state(params)
        p1, s1, _ = adamw_update(params, grads, opt, 1e-3, AdamWConfig())
        p2, s2, m = adamw_update(params, grads, opt, 1e-3, AdamWConfig(),
                                 skip_nonfinite=True)
        assert bool(m["updates_finite"])
        assert _trees_bitwise_equal((p1, s1), (p2, s2))

    def test_extra_finite_flag_forces_skip(self):
        # a NaN loss with finite grads (e.g. an aux-path NaN) must still skip
        params = _params()
        grads = jax.tree_util.tree_map(lambda p: 0.1 * p, params)
        opt = init_opt_state(params)
        new_p, new_s, m = adamw_update(
            params, grads, opt, 1e-3, AdamWConfig(),
            skip_nonfinite=True, extra_finite=jnp.asarray(False))
        assert not bool(m["updates_finite"])
        assert _trees_bitwise_equal(new_p, params)

    def test_inf_grads_also_skip(self):
        params = _params()
        grads = jax.tree_util.tree_map(lambda p: 0.1 * p, params)
        grads["embed"]["embedding"] = (
            grads["embed"]["embedding"].at[0, 0].set(jnp.inf))
        opt = init_opt_state(params)
        new_p, _, m = adamw_update(params, grads, opt, 1e-3, AdamWConfig(),
                                   skip_nonfinite=True)
        assert not bool(m["updates_finite"])
        assert _trees_bitwise_equal(new_p, params)


class TestHealthOptState:
    def test_init_and_specs_shapes_match(self, cpu_mesh):
        from jax.sharding import PartitionSpec as P

        params = _params()
        state = init_opt_state(params, health=True)
        assert set(state["health"]) == {
            "steps_seen", "nonfinite_count", "skipped_count",
            "last_nonfinite_step"}
        assert int(state["health"]["last_nonfinite_step"]) == -1
        pspecs = jax.tree_util.tree_map(lambda _: P(), params)
        ospecs = opt_state_specs(params, pspecs, cpu_mesh, health=True)
        # spec tree structure must match the state tree structure exactly
        assert (jax.tree_util.tree_structure(state)
                == jax.tree_util.tree_structure(
                    jax.tree_util.tree_map(
                        lambda x: x, ospecs,
                        is_leaf=lambda x: isinstance(x, P))))


# ---------------------------------------------------------------------------
# make_train_step: in-graph probes on a real tiny llama step
# ---------------------------------------------------------------------------


def _llama_step(policy_name="skip_update", param_norm=True):
    from neuronx_distributed_training_tpu.models import llama
    from neuronx_distributed_training_tpu.optim.lr import constant_lr
    from neuronx_distributed_training_tpu.trainer.step import make_train_step

    cfg = llama.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
        num_attention_heads=4, num_kv_heads=2, max_position_embeddings=16)
    policy = DtypePolicy()
    params = llama.init_params(jax.random.PRNGKey(0), cfg, policy)
    opt = init_opt_state(params, policy, health=True)
    hc = HealthConfig(enabled=True, policy=policy_name, param_norm=param_norm)

    def loss_fn(p, batch, key):
        return llama.forward(p, batch, cfg, policy)

    step = jax.jit(make_train_step(
        loss_fn, AdamWConfig(), constant_lr(1e-3), policy, health_cfg=hc))
    ids = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64,
                             dtype=jnp.int32)
    clean = {"input_ids": ids, "labels": ids,
             "loss_mask": jnp.ones((4, 16), jnp.float32)}
    poisoned = dict(clean, loss_mask=jnp.full((4, 16), jnp.nan, jnp.float32))
    return step, params, opt, clean, poisoned


class TestTrainStepHealth:
    def test_healthy_step_metrics(self):
        step, params, opt, clean, _ = _llama_step()
        _, o1, m = step(params, opt, clean, jax.random.PRNGKey(2))
        assert float(m["health/updates_finite"]) == 1.0
        assert float(m["health/loss_finite"]) == 1.0
        assert float(m["health/nonfinite_count"]) == 0.0
        assert float(m["health/last_nonfinite_step"]) == -1.0
        assert m["health/param_norm"] > 0.0
        groups = {k for k in m if k.startswith("health/grad_norm/")}
        assert "health/grad_norm/layers/attn" in groups
        assert "health/grad_norm/embed" in groups
        assert int(o1["health"]["steps_seen"]) == 1

    def test_nan_batch_suppresses_update_bitwise(self):
        step, params, opt, clean, poisoned = _llama_step("skip_update")
        p1, o1, _ = step(params, opt, clean, jax.random.PRNGKey(2))
        p2, o2, m = step(p1, o1, poisoned, jax.random.PRNGKey(3))
        assert float(m["health/updates_finite"]) == 0.0
        assert float(m["health/skipped_count"]) == 1.0
        assert float(m["health/last_nonfinite_step"]) == 1.0
        assert _trees_bitwise_equal(p2, p1)
        # AdamW's own step counter froze; the invocation counter advanced
        assert int(o2["step"]) == int(o1["step"])
        assert int(o2["health"]["steps_seen"]) == 2
        # training resumes: the next clean step applies a normal update
        p3, o3, m3 = step(p2, o2, clean, jax.random.PRNGKey(4))
        assert float(m3["health/updates_finite"]) == 1.0
        assert float(m3["health/nonfinite_count"]) == 1.0
        assert np.isfinite(float(m3["loss"]))
        assert not _trees_bitwise_equal(p3, p2)
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree_util.tree_leaves(p3))

    def test_dump_and_continue_counts_but_applies(self):
        # without skip_update the poisoned update flows through (and the
        # counters record it) — the documented dump_and_continue semantics
        step, params, opt, clean, poisoned = _llama_step("dump_and_continue")
        p1, o1, _ = step(params, opt, clean, jax.random.PRNGKey(2))
        p2, o2, m = step(p1, o1, poisoned, jax.random.PRNGKey(3))
        assert float(m["health/nonfinite_count"]) == 1.0
        assert float(m["health/skipped_count"]) == 0.0
        assert not _trees_bitwise_equal(p2, p1)  # the NaN update applied
        assert int(o2["step"]) == int(o1["step"]) + 1

    def test_param_norm_knob_off(self):
        step, params, opt, clean, _ = _llama_step(param_norm=False)
        _, _, m = step(params, opt, clean, jax.random.PRNGKey(2))
        assert "health/param_norm" not in m

    def test_disabled_health_adds_no_keys(self):
        from neuronx_distributed_training_tpu.models import llama
        from neuronx_distributed_training_tpu.optim.lr import constant_lr
        from neuronx_distributed_training_tpu.trainer.step import (
            make_train_step,
        )

        cfg = llama.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
            num_attention_heads=4, num_kv_heads=2, max_position_embeddings=16)
        policy = DtypePolicy()
        params = llama.init_params(jax.random.PRNGKey(0), cfg, policy)
        opt = init_opt_state(params, policy)

        def loss_fn(p, batch, key):
            return llama.forward(p, batch, cfg, policy)

        step = make_train_step(loss_fn, AdamWConfig(), constant_lr(1e-3),
                               policy, health_cfg=HealthConfig(enabled=False))
        ids = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64,
                                 dtype=jnp.int32)
        _, o, m = jax.jit(step)(params, opt,
                                {"input_ids": ids, "labels": ids},
                                jax.random.PRNGKey(2))
        assert not any(k.startswith("health/") for k in m)
        assert "health" not in o


# ---------------------------------------------------------------------------
# HealthMonitor: ring buffer + bundles
# ---------------------------------------------------------------------------


def _mon(tmp_path, **kw):
    defaults = dict(enabled=True, policy="dump_and_continue",
                    ring_buffer_steps=4)
    defaults.update(kw)
    return HealthMonitor(HealthConfig(**defaults), dump_dir=tmp_path,
                         run_facts={"model_family": "LlamaConfig"})


class TestHealthMonitor:
    def _feed(self, mon, steps, bad_at=()):
        count = 0
        for s in range(steps):
            if s in bad_at:
                count += 1
            mon.record(s, {"loss": float(s), "health/nonfinite_count": count},
                       fingerprint={"arg0['x']": "f32[8]"},
                       spans={"dispatch": 0.1 * s})
        return count

    def test_healthy_boundary_is_noop(self, tmp_path):
        mon = _mon(tmp_path)
        self._feed(mon, 5)
        assert mon.check_boundary(5, {"health/nonfinite_count": 0.0}) is None
        assert not list(Path(tmp_path).glob("anomaly_*"))

    def test_missing_counter_is_noop(self, tmp_path):
        mon = _mon(tmp_path)
        assert mon.check_boundary(5, {"loss": 1.0}) is None

    def test_anomaly_dumps_bundle_once(self, tmp_path):
        mon = _mon(tmp_path)
        self._feed(mon, 4, bad_at={2})
        fetched = {"health/nonfinite_count": 1.0,
                   "health/last_nonfinite_step": 2.0, "loss": float("nan")}
        assert mon.check_boundary(4, fetched) == "dump_and_continue"
        # same counter at the next boundary: no new bundle, no action
        assert mon.check_boundary(5, fetched) is None
        bundles = sorted(Path(tmp_path).glob("anomaly_*"))
        assert len(bundles) == 1
        summary = json.loads((bundles[0] / "anomaly.json").read_text())
        assert summary["anomaly_step"] == 2
        assert summary["trigger_step"] == 4
        assert summary["rng"] == {"seed": 0, "fold_in": 2}
        assert "run_summary.json" in summary["compile_census"]
        assert summary["run_facts"]["model_family"] == "LlamaConfig"

    def test_ring_holds_min_k_n_prior_steps(self, tmp_path):
        # anomaly at step k with depth N: ring must hold >= min(k, N) priors
        for k, n in ((2, 8), (6, 4)):
            mon = _mon(tmp_path / f"k{k}", ring_buffer_steps=n)
            self._feed(mon, k + 1, bad_at={k})
            mon.check_boundary(k + 1, {"health/nonfinite_count": 1.0,
                                       "health/last_nonfinite_step": float(k)})
            bundle = next((Path(tmp_path) / f"k{k}").glob("anomaly_*"))
            ring = json.loads((bundle / "ring.json").read_text())
            prior = [e for e in ring if e["step"] < k]
            assert len(prior) >= min(k, n - 1), (k, n, [e["step"] for e in ring])
            assert ring[-1]["step"] == k
            # forensic fields present per entry
            assert ring[-1]["fingerprint"] == {"arg0['x']": "f32[8]"}
            assert ring[-1]["rng"] == {"seed": 0, "fold_in": k}
            assert "spans_cumulative" in ring[-1]

    def test_max_bundles_cap(self, tmp_path):
        mon = _mon(tmp_path, max_bundles=2)
        for step in (1, 2, 3):
            mon.record(step, {"health/nonfinite_count": step})
            mon.check_boundary(step + 1,
                               {"health/nonfinite_count": float(step),
                                "health/last_nonfinite_step": float(step)})
        assert len(list(Path(tmp_path).glob("anomaly_*"))) == 2

    def test_multiple_bad_steps_in_one_window_each_get_bundles(self, tmp_path):
        # counter jumps by 2 inside one logging window: BOTH still-buffered
        # bad steps must get their own bundle, not just last_nonfinite_step
        mon = _mon(tmp_path, ring_buffer_steps=8)
        for s in range(6):
            bad = s in (3, 5)
            mon.record(s, {"health/updates_finite": 0.0 if bad else 1.0,
                           "health/nonfinite_count": float(sum(
                               x <= s for x in (3, 5)))})
        assert mon.check_boundary(
            6, {"health/nonfinite_count": 2.0,
                "health/last_nonfinite_step": 5.0}) == "dump_and_continue"
        assert sorted(b.name for b in Path(tmp_path).glob("anomaly_*")) == [
            "anomaly_00000003", "anomaly_00000005"]

    def test_seed_counters_suppresses_resume_retrigger(self, tmp_path):
        # a fresh monitor (restart) must not re-trigger on a counter that a
        # previous incarnation already handled
        mon = _mon(tmp_path)
        mon.seed_counters(2)
        assert mon.check_boundary(500, {"health/nonfinite_count": 2.0}) is None
        assert not list(Path(tmp_path).glob("anomaly_*"))

    def test_resume_extends_prior_anomaly_trail(self, tmp_path):
        # run_summary.json's anomaly list survives a restart: the new
        # monitor seeds from it and appends instead of overwriting
        import json as _json

        prior = [{"step": 100, "bundle": "anomaly_00000100",
                  "policy": "skip_update"}]
        (tmp_path / "run_summary.json").write_text(
            _json.dumps({"anomalies": prior}))
        written = {}
        mon = HealthMonitor(
            HealthConfig(enabled=True, ring_buffer_steps=4),
            dump_dir=tmp_path, write_run_summary=written.update)
        mon.record(900, {"health/nonfinite_count": 1})
        mon.check_boundary(901, {"health/nonfinite_count": 1.0,
                                 "health/last_nonfinite_step": 900.0})
        assert [a["step"] for a in written["anomalies"]] == [100, 900]

    def test_failed_write_burns_neither_dedupe_nor_budget(self, tmp_path,
                                                          monkeypatch):
        import neuronx_distributed_training_tpu.telemetry.flight_recorder as fr

        mon = _mon(tmp_path, max_bundles=1)
        calls = {"n": 0}
        orig = fr.json.dump

        def flaky(*a, **kw):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("No space left on device")
            return orig(*a, **kw)

        monkeypatch.setattr(fr.json, "dump", flaky)
        assert mon.dump(3) is None  # transient ENOSPC
        bundle = mon.dump(3)  # retry: dedupe slot and cap were not consumed
        assert bundle is not None and (bundle / "anomaly.json").exists()

    def test_malformed_prior_trail_entry_skipped_not_fatal(self, tmp_path):
        (tmp_path / "run_summary.json").write_text(json.dumps({"anomalies": [
            {"step": 1, "bundle": "anomaly_00000001", "policy": "p"},
            {"bundle": "anomaly_nostep"},  # malformed: no step
            {"step": 3, "bundle": "anomaly_00000003", "policy": "p"}]}))
        mon = _mon(tmp_path)
        # one bad entry must not drop the rest of the prior trail
        assert [a["step"] for a in mon.anomalies] == [1, 3]

    def test_write_failed_anomaly_retries_at_next_boundary(self, tmp_path,
                                                           monkeypatch):
        import neuronx_distributed_training_tpu.telemetry.flight_recorder as fr

        mon = _mon(tmp_path)
        mon.record(2, {"health/nonfinite_count": 1})
        calls = {"n": 0}
        orig = fr.json.dump

        def flaky(*a, **kw):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("No space left on device")
            return orig(*a, **kw)

        monkeypatch.setattr(fr.json, "dump", flaky)
        fetched = {"health/nonfinite_count": 1.0,
                   "health/last_nonfinite_step": 2.0}
        # first boundary: write fails; the comparator must roll back so the
        # SAME counter value re-triggers at the next boundary
        assert mon.check_boundary(3, fetched) == "dump_and_continue"
        assert not list(Path(tmp_path).glob("anomaly_*"))
        assert mon.check_boundary(4, fetched) == "dump_and_continue"
        assert len(list(Path(tmp_path).glob("anomaly_*"))) == 1
        # and once dumped, the counter no longer triggers
        assert mon.check_boundary(5, fetched) is None

    def test_hang_dump_bypasses_anomaly_cap(self, tmp_path):
        mon = _mon(tmp_path, max_bundles=1)
        mon.record(1, {"health/nonfinite_count": 1})
        mon.check_boundary(2, {"health/nonfinite_count": 1.0,
                               "health/last_nonfinite_step": 1.0})
        # anomaly budget exhausted; the hang's stacks must still land
        bundle = mon.dump_hang(5, "host_sync", "stack text")
        assert bundle is not None and (bundle / "stacks.txt").exists()

    def test_run_summary_callback(self, tmp_path):
        written = {}
        mon = HealthMonitor(
            HealthConfig(enabled=True, ring_buffer_steps=4),
            dump_dir=tmp_path, write_run_summary=written.update)
        mon.record(0, {"health/nonfinite_count": 1})
        mon.check_boundary(1, {"health/nonfinite_count": 1.0,
                               "health/last_nonfinite_step": 0.0})
        assert written["anomalies"][0]["step"] == 0
        assert written["anomalies"][0]["bundle"].startswith("anomaly_")


# ---------------------------------------------------------------------------
# hang watchdog
# ---------------------------------------------------------------------------


class TestHangWatchdog:
    def test_fast_block_does_not_fire(self, tmp_path):
        mon = _mon(tmp_path)
        wd = HangWatchdog(5.0, mon, abort=False)
        with wd.guard("host_sync", 3):
            pass
        time.sleep(0.05)
        assert wd.fired is False
        assert not list(Path(tmp_path).glob("hang_*"))

    def test_hang_dumps_stacks_without_device_fetch(self, tmp_path):
        mon = _mon(tmp_path)
        mon.record(7, {"loss": jnp.asarray(1.0),
                       "health/nonfinite_count": jnp.asarray(0)},
                   fingerprint={"arg0['x']": "f32[8]"})
        wd = HangWatchdog(0.05, mon, abort=False)
        with wd.guard("host_sync", 7):
            time.sleep(0.4)
        assert wd.fired is True
        bundle = next(Path(tmp_path).glob("hang_*"))
        assert (bundle / "stacks.txt").exists()
        stacks = (bundle / "stacks.txt").read_text()
        assert "thread" in stacks
        summary = json.loads((bundle / "anomaly.json").read_text())
        assert summary["kind"] == "hang"
        assert summary["hung_operation"] == "host_sync"
        ring = json.loads((bundle / "ring.json").read_text())
        # device arrays must NOT have been fetched (hung backend): metric
        # values are replaced by their key list
        assert ring[-1]["metrics"] == {"keys": ["health/nonfinite_count",
                                                "loss"]}

    def test_fires_at_most_once_per_process(self, tmp_path):
        # under abort=False a chronically slow boundary must not write a
        # hang bundle per boundary (hang bundles bypass max_bundles on the
        # strength of this guarantee)
        mon = _mon(tmp_path)
        wd = HangWatchdog(0.05, mon, abort=False)
        with wd.guard("host_sync", 1):
            time.sleep(0.3)
        with wd.guard("host_sync", 2):
            time.sleep(0.3)
        assert wd.fired is True
        assert len(list(Path(tmp_path).glob("hang_*"))) == 1


# ---------------------------------------------------------------------------
# trainer integration: fault injection per policy through a real fit()
# ---------------------------------------------------------------------------


def _tiny_cfg(tmp_path, *, policy, max_steps=6, ring=8, log_every=1):
    from neuronx_distributed_training_tpu.config.loader import load_config

    return load_config({
        "name": "health", "model_source": "hf", "seed": 7,
        "trainer": {"max_steps": max_steps, "log_every_n_steps": log_every},
        "exp_manager": {"exp_dir": str(tmp_path / "exp"),
                        "create_tensorboard_logger": False,
                        "log_files": False,
                        "telemetry": {"health": {
                            "enabled": True, "policy": policy,
                            "ring_buffer_steps": ring}}},
        "distributed_strategy": {"tensor_model_parallel_size": 2,
                                 "sequence_parallel": True},
        "data": {"global_batch_size": 8, "micro_batch_size": 1,
                 "seq_length": 32, "synthetic": True},
        "model": {"vocab_size": 128, "hidden_size": 64,
                  "intermediate_size": 128, "num_layers": 2,
                  "num_attention_heads": 4, "num_key_value_heads": 2,
                  "max_position_embeddings": 32,
                  "optim": {"name": "adamw_fp32OptState", "lr": 1e-3}},
        "precision": {"type": "mixed_precision"},
    })


def _nan_data_module(nan_steps, seed=3):
    from neuronx_distributed_training_tpu.data import SyntheticDataModule

    class NaNInjecting(SyntheticDataModule):
        """Synthetic LM batches with a NaN loss_mask at chosen step indices.

        The mask rides EVERY batch (all-ones normally) so the abstract batch
        signature never changes — the injection is a pure value fault, not a
        retrace."""

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self._yielded = 0

        def global_batches(self):
            for b in super().global_batches():
                mask = np.ones_like(b["input_ids"], np.float32)
                if self._yielded in nan_steps:
                    mask[:] = np.nan
                self._yielded += 1
                yield dict(b, loss_mask=mask)

    return NaNInjecting(vocab_size=128, seq_len=32, global_batch_size=8,
                        seed=seed)


def _run(tmp_path, policy, nan_steps=frozenset({2}), **cfg_kw):
    from neuronx_distributed_training_tpu.trainer.loop import Trainer

    cfg = _tiny_cfg(tmp_path, policy=policy, **cfg_kw)
    t = Trainer.from_config(cfg, data_module=_nan_data_module(nan_steps),
                            enable_checkpointing=False)
    metrics = t.fit()
    return t, metrics, Path(t.exp.log_dir)


class TestFaultInjectionPolicies:
    def test_skip_update_suppresses_and_resumes(self, tmp_path, devices8):
        k = 2
        t, m, log_dir = _run(tmp_path, "skip_update", {k})
        assert t.step == 6  # training resumed to completion
        assert m["health/nonfinite_count"] == 1.0
        assert m["health/skipped_count"] == 1.0
        assert m["health/last_nonfinite_step"] == float(k)
        assert np.isfinite(m["loss"])
        # the skipped update left the params clean: every leaf finite
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree_util.tree_leaves(t.params))
        bundles = sorted(log_dir.glob("anomaly_*"))
        assert len(bundles) == 1  # exactly one bundle for the one bad step
        ring = json.loads((bundles[0] / "ring.json").read_text())
        assert len([e for e in ring if e["step"] < k]) >= min(k, 8)
        # bundles must be STRICT JSON: the bad step's nan loss/grad_norm are
        # serialized as strings, never bare NaN tokens
        for f in ("ring.json", "anomaly.json"):
            json.dumps(json.loads((bundles[0] / f).read_text()),
                       allow_nan=False)
        bad_entry = next(e for e in ring if e["step"] == k)
        assert bad_entry["metrics"]["loss"] == "nan"
        summary = json.loads((log_dir / "run_summary.json").read_text())
        assert summary["anomalies"] == [{"step": k,
                                         "bundle": bundles[0].name,
                                         "policy": "skip_update"}]

    def test_dump_and_continue_keeps_training(self, tmp_path, devices8):
        t, m, log_dir = _run(tmp_path, "dump_and_continue", {2})
        assert t.step == 6  # training ran to completion
        # documented semantics: the poisoned update APPLIED, so params are
        # NaN from step 2 on and every later step is non-finite too (2..5);
        # each newly-bad step gets its own bundle (deduped per step, capped
        # at max_bundles) — this cascade is exactly why skip_update exists
        assert m["health/nonfinite_count"] == 4.0
        assert m["health/skipped_count"] == 0.0
        bundles = sorted(log_dir.glob("anomaly_*"))
        assert [b.name for b in bundles] == [
            f"anomaly_{s:08d}" for s in (2, 3, 4, 5)]

    def test_halt_stops_without_checkpoint(self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        class FakeCheckpointer:
            """Records save() calls; stands in for orbax (absent on this
            image) so the halt-never-checkpoints contract is pinned."""

            class config:
                every_n_train_steps = 5

            def __init__(self):
                self.saved_steps = []

            def latest_step(self):
                return None

            def save(self, state, metrics=None):
                self.saved_steps.append(int(state.step))
                return True

            def wait(self):
                pass

            def close(self):
                pass

        cfg = _tiny_cfg(tmp_path, policy="halt")
        t = Trainer.from_config(cfg, data_module=_nan_data_module({2}),
                                enable_checkpointing=False)
        t.checkpointer = FakeCheckpointer()
        t.fit()
        # with log_every=1 the anomaly at step 2 is detected at boundary 3
        assert t.step == 3
        log_dir = Path(t.exp.log_dir)
        assert len(list(log_dir.glob("anomaly_*"))) == 1
        # halt must NOT checkpoint the poisoned state — neither the
        # stop-path save nor the final save may run
        assert t.checkpointer.saved_steps == []

    def test_resume_from_pre_health_checkpoint(self, tmp_path, devices8):
        """Flipping telemetry.health on must not strand an existing run: a
        checkpoint written WITHOUT the health subtree restores with fresh
        counters instead of crashing on the tree mismatch."""
        from neuronx_distributed_training_tpu.checkpoint import TrainState
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _tiny_cfg(tmp_path, policy="skip_update")
        t = Trainer.from_config(cfg, data_module=_nan_data_module(frozenset()),
                                enable_checkpointing=False)
        legacy_opt = {k: v for k, v in t.opt_state.items() if k != "health"}

        class LegacyCheckpointer:
            """Restores a pre-health checkpoint: raises on a template that
            carries the health subtree (the orbax structure-mismatch), like
            a real store would."""

            config = type("C", (), {"every_n_train_steps": 0})

            def latest_step(self):
                return 4

            def restore(self, params, opt_state, **kw):
                if "health" in opt_state:
                    raise ValueError("tree structure mismatch: 'health'")
                return TrainState(params=params, opt_state=opt_state,
                                  step=4, consumed_samples=32)

            def wait(self):
                pass

            def close(self):
                pass

        t.checkpointer = LegacyCheckpointer()
        assert t.maybe_resume() is True
        assert t.step == 4
        assert "health" in t.opt_state  # fresh counters re-attached
        assert int(t.opt_state["health"]["nonfinite_count"]) == 0
        # steps_seen realigned with the restored trainer step: future
        # last_nonfinite_step values (steps_seen - 1 at the bad step) must
        # name real trainer steps, not a counter restarted at 0
        assert int(t.opt_state["health"]["steps_seen"]) == 4
        assert set(t.opt_state) == set(legacy_opt) | {"health"}

    def test_census_write_failure_keeps_compiled_step(self, tmp_path,
                                                      devices8, monkeypatch):
        """A run_summary.json write error must not discard the finished
        executable and force a second compile."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _tiny_cfg(tmp_path, policy="skip_update", max_steps=2)
        t = Trainer.from_config(cfg, data_module=_nan_data_module(frozenset()),
                                enable_checkpointing=False)
        monkeypatch.setattr(
            t.exp, "write_run_summary",
            lambda *_a, **_k: (_ for _ in ()).throw(OSError("disk full")))
        t.fit()
        # the loop still swapped in (and ran) the AOT executable
        assert not hasattr(t.train_step, "lower")

    def test_detection_latency_is_log_interval(self, tmp_path, devices8):
        # log_every=3, anomaly at step 2 -> detected at boundary step 3;
        # skip_update protected the params in-graph at zero latency either way
        t, m, log_dir = _run(tmp_path, "skip_update", {2}, log_every=3)
        assert t.step == 6
        bundles = sorted(log_dir.glob("anomaly_*"))
        assert len(bundles) == 1
        assert json.loads(
            (bundles[0] / "anomaly.json").read_text())["trigger_step"] == 3


# ---------------------------------------------------------------------------
# healthy-path overhead contract: AOT once, zero retraces, health in sinks
# ---------------------------------------------------------------------------


class TestHealthyPathOverhead:
    @pytest.fixture(scope="class")
    def healthy_run(self, tmp_path_factory, devices8):
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        tmp_path = tmp_path_factory.mktemp("healthy")
        cfg = _tiny_cfg(tmp_path, policy="skip_update")
        t = Trainer.from_config(cfg, data_module=_nan_data_module(frozenset()),
                                enable_checkpointing=False)
        metrics = t.fit()
        return t, metrics, Path(t.exp.log_dir)

    def test_aot_executable_swapped_in(self, healthy_run):
        # the census AOT-compiles ONCE and the loop runs that executable:
        # health riding the same jit means no second compile ever happened
        t, _, _ = healthy_run
        assert not hasattr(t.train_step, "lower")

    def test_zero_retraces(self, healthy_run):
        t, _, log_dir = healthy_run
        summary = json.loads((log_dir / "run_summary.json").read_text())
        assert "retrace_events" not in summary
        assert "anomalies" not in summary

    def test_health_metrics_flow_through_sinks(self, healthy_run):
        _, _, log_dir = healthy_run
        records = [json.loads(l) for l in
                   (log_dir / "metrics.jsonl").read_text().splitlines()]
        last = records[-1]
        assert last["health/updates_finite"] == 1.0
        assert last["health/nonfinite_count"] == 0.0
        assert any(k.startswith("health/grad_norm/") for k in last)
        # and the census/goodput schema of PR 2 is intact alongside
        summary = json.loads((log_dir / "run_summary.json").read_text())
        assert summary["compile_seconds"] > 0
        assert "collectives" in summary

    def test_no_bundles_written(self, healthy_run):
        _, _, log_dir = healthy_run
        assert not list(log_dir.glob("anomaly_*"))
        assert not list(log_dir.glob("hang_*"))


class TestDispatchAheadContractWithHealth:
    def test_no_host_sync_between_boundaries(self, tmp_path, devices8):
        """Health must add ZERO host syncs between logging boundaries: with
        an instrumented step emitting health metrics, values are converted
        to host floats only at boundary steps (the monitor ring-buffers
        device references without touching them)."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _tiny_cfg(tmp_path, policy="skip_update", max_steps=6,
                        log_every=3)
        t = Trainer.from_config(cfg, data_module=_nan_data_module(frozenset()),
                                enable_checkpointing=False)

        conversions: list[int] = []

        class _Scalar:
            def __init__(self, step, value=1.0):
                self.step, self.value = step, value

            def __float__(self):
                conversions.append(self.step)
                return self.value

        real_params, real_opt = t.params, t.opt_state

        def fake_step(params, opt_state, batch, key):
            return real_params, real_opt, {
                "loss": _Scalar(t.step),
                "grad_norm": _Scalar(t.step),
                "health/updates_finite": _Scalar(t.step),
                "health/nonfinite_count": _Scalar(t.step, 0.0),
                "health/last_nonfinite_step": _Scalar(t.step, -1.0),
            }

        t.train_step = fake_step
        t.fit()
        assert conversions, "boundaries must fetch metrics"
        # pre-increment step ids 2 and 5 -> boundaries at steps 3 and 6; the
        # ring-buffered steps 0,1,3,4 must never have been fetched
        assert set(conversions) == {2, 5}, sorted(set(conversions))


# ---------------------------------------------------------------------------
# tools/anomaly_report.py smoke
# ---------------------------------------------------------------------------


def _load_tool(name):
    path = Path(__file__).resolve().parents[1] / "tools" / f"{name}.py"
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestAnomalyReport:
    def _bundle(self, tmp_path):
        mon = _mon(tmp_path)
        for s in range(3):
            mon.record(s, {
                "loss": 4.0 - s if s < 2 else float("nan"),
                "grad_norm": 1.0 if s < 2 else float("nan"),
                "health/updates_finite": 1.0 if s < 2 else 0.0,
                "health/param_norm": 10.0 + 0.5 * s,
                "health/nonfinite_count": 0.0 if s < 2 else 1.0,
                "health/grad_norm/layers/attn": 0.5,
            }, fingerprint={"arg0['input_ids']": "int32[8,32]"})
        mon.check_boundary(3, {"health/nonfinite_count": 1.0,
                               "health/last_nonfinite_step": 2.0})
        return tmp_path

    def test_renders_bundle_dir_and_run_dir(self, tmp_path, capsys):
        ar = _load_tool("anomaly_report")
        run_dir = self._bundle(tmp_path)
        assert ar.main([str(run_dir)]) == 0  # run dir: newest bundle picked
        out = capsys.readouterr().out
        for needle in ("anomaly bundle — step 2", "dump_and_continue",
                       "fold_in(PRNGKey(0), 2)", "ring buffer", "layers/attn",
                       "pnorm_drift", "int32[8,32]"):
            assert needle in out, (needle, out)
        bundle = next(run_dir.glob("anomaly_*"))
        assert ar.main([str(bundle)]) == 0  # direct bundle path too

    def test_missing_bundle_errors(self, tmp_path):
        ar = _load_tool("anomaly_report")
        assert ar.main([str(tmp_path)]) == 2

    def test_newest_bundle_picked_by_step_not_name(self, tmp_path):
        # lexicographic order would rank hang_* above every anomaly_*
        ar = _load_tool("anomaly_report")
        for name, step in (("hang_00000010", 10), ("anomaly_00000500", 500)):
            d = tmp_path / name
            d.mkdir()
            (d / "anomaly.json").write_text(json.dumps(
                {"kind": name.split("_")[0], "anomaly_step": step}))
        assert ar.find_bundle(str(tmp_path)).endswith("anomaly_00000500")

    def test_renders_real_trainer_bundle(self, tmp_path, devices8, capsys):
        # the renderer must accept exactly what a real anomalous fit() writes
        ar = _load_tool("anomaly_report")
        _, _, log_dir = _run(tmp_path, "skip_update", {1}, max_steps=3)
        assert ar.main([str(log_dir)]) == 0
        out = capsys.readouterr().out
        assert "anomaly bundle — step 1" in out
        assert "per-group grad norms" in out

    def test_metrics_report_lists_anomalies(self, tmp_path, devices8, capsys):
        mr = _load_tool("metrics_report")
        _, _, log_dir = _run(tmp_path, "skip_update", {1}, max_steps=3)
        assert mr.main([str(log_dir)]) == 0
        out = capsys.readouterr().out
        assert "anomalies (1 forensic bundle" in out
        assert "anomaly_00000001" in out

    def test_metrics_report_tolerates_malformed_trail(self, tmp_path, capsys):
        mr = _load_tool("metrics_report")
        (tmp_path / "run_summary.json").write_text(json.dumps({
            "anomalies": [{"step": 2, "bundle": "anomaly_00000002",
                           "policy": "halt"},
                          "not-a-dict", {"bundle": "anomaly_nostep"}]}))
        assert mr.main([str(tmp_path / "run_summary.json")]) == 0
        out = capsys.readouterr().out
        assert "anomaly_00000002" in out
        assert "unreadable entry" in out

    def test_bench_json_float_is_nan_safe(self):
        import bench

        assert bench.json_float(float("nan")) == "nan"
        assert bench.json_float(float("-inf")) == "-inf"
        assert bench.json_float(1.23456) == pytest.approx(1.2346)
        # the whole point: the payload stays valid JSON for a diverging run
        json.dumps({"final_grad_norm": bench.json_float(float("nan"))})
