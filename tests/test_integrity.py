"""Checkpoint integrity: digest sidecars, verified restore with walk-back,
quarantine, the post-commit save audit, corruption drills, and the
data-stall watchdog (docs/elasticity.md "Integrity & walk-back").

The corrupt-restore matrix is the heart: every injection kind (byte-flip /
truncate / delete-item / stale-sidecar) × (same-world resume, dp-change
elastic resume) must end in quarantine + walk-back + continuity — no human
intervention, no crash loop.
"""

import json
import logging
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from neuronx_distributed_training_tpu.checkpoint import (
    CheckpointConfig,
    Checkpointer,
    CheckpointIntegrityError,
    IntegrityConfig,
    TrainState,
    inject_corruption,
)
from neuronx_distributed_training_tpu.checkpoint import integrity as I
from neuronx_distributed_training_tpu.config.loader import load_config
from neuronx_distributed_training_tpu.data.loader import (
    DataStallError,
    PrefetchIterator,
)

from elastic_drill import read_losses, run_corruption_drill, tiny_llama_config


# ---------------------------------------------------------------------------
# knob block
# ---------------------------------------------------------------------------


class TestIntegrityConfig:
    def test_defaults(self):
        ic = IntegrityConfig.from_config(None)
        assert ic.enabled and ic.verify_restore and ic.quarantine
        assert not ic.audit
        assert ic.audit_deadline_seconds == 120.0

    def test_bare_bool_toggles_enabled(self):
        assert IntegrityConfig.from_config(True).enabled
        assert not IntegrityConfig.from_config(False).enabled

    def test_unknown_key_has_did_you_mean(self):
        with pytest.raises(ValueError, match="quarantine"):
            IntegrityConfig.from_config({"quarantene": True})

    def test_ill_typed_rejected(self):
        with pytest.raises(ValueError, match="boolean"):
            IntegrityConfig.from_config({"audit": "yes"})
        with pytest.raises(ValueError, match="number"):
            IntegrityConfig.from_config({"audit_deadline_seconds": "fast"})
        with pytest.raises(ValueError, match=">= 0"):
            IntegrityConfig.from_config({"audit_deadline_seconds": -1})

    def test_checkpoint_block_unknown_key(self):
        with pytest.raises(ValueError, match="integrity"):
            I.parse_checkpoint_block({"integrety": {}})

    def test_loader_validates_the_block(self):
        raw = {
            "trainer": {"max_steps": 1},
            "exp_manager": {"checkpoint": {"integrity": {"enabeld": True}}},
        }
        with pytest.raises(ValueError, match="enabled"):
            load_config(raw)

    def test_config_flows_into_checkpoint_config(self):
        cfg = CheckpointConfig.from_config({
            "exp_manager": {"checkpoint": {"integrity": {
                "audit": True, "audit_deadline_seconds": 7}}},
        })
        assert cfg.integrity.audit
        assert cfg.integrity.audit_deadline_seconds == 7.0


# ---------------------------------------------------------------------------
# sidecar digests
# ---------------------------------------------------------------------------


def _trees(scale=1.0):
    params = {"w": jnp.full((8, 4), scale, jnp.float32),
              "b": jnp.arange(4, dtype=jnp.bfloat16)}
    opt = {"mu": jax.tree_util.tree_map(jnp.zeros_like, params),
           "master": jax.tree_util.tree_map(
               lambda x: x.astype(jnp.float32), params),
           "step": jnp.asarray(3, jnp.int32)}
    return params, opt


class TestSidecar:
    def test_deterministic_and_grouped(self):
        p, o = _trees()
        s1 = I.build_sidecar(step=3, params=p, opt_state=o,
                             meta={"step": 3}, manifest={"world_size": 8})
        s2 = I.build_sidecar(step=3, params=p, opt_state=o,
                             meta={"step": 3}, manifest={"world_size": 8})
        assert s1 == s2
        assert s1["content"] is True
        # opt_state splits per top-level key; params stays one group
        assert {"params", "opt_state/mu", "opt_state/master",
                "opt_state/step"} <= set(s1["groups"])
        assert all(v["leaves"] >= 1 and len(v["digest"]) == 32
                   for v in s1["groups"].values())

    def test_value_change_flips_only_its_group(self):
        p, o = _trees()
        base = I.build_sidecar(step=3, params=p, opt_state=o, meta={})
        o2 = dict(o, mu=jax.tree_util.tree_map(lambda x: x + 1, o["mu"]))
        changed = I.build_sidecar(step=3, params=p, opt_state=o2, meta={})
        assert (changed["groups"]["opt_state/mu"]["digest"]
                != base["groups"]["opt_state/mu"]["digest"])
        assert (changed["groups"]["params"]["digest"]
                == base["groups"]["params"]["digest"])
        assert (changed["groups"]["opt_state/master"]["digest"]
                == base["groups"]["opt_state/master"]["digest"])

    def test_json_digest_normalizes(self):
        assert I.json_digest({"a": (1, 2)}) == I.json_digest({"a": [1, 2]})
        assert I.json_digest({"a": 1}) != I.json_digest({"a": 2})

    def test_structure_summary_carries_shapes_dtypes(self):
        p, o = _trees()
        s = I.build_sidecar(step=1, params=p, opt_state=o, meta={})
        w = s["tree"]["params"]["['w']"]
        assert w == {"dtype": "float32", "shape": [8, 4]}
        assert s["tree"]["params"]["['b']"]["dtype"] == "bfloat16"


# ---------------------------------------------------------------------------
# save → verify round trip
# ---------------------------------------------------------------------------


def _save_steps(tmp_path, steps=(1, 2), *, integrity=None, manifest=True,
                top_k=5, **cfg_over):
    cfg = CheckpointConfig(
        dir=tmp_path, async_save=False, save_top_k=top_k,
        integrity=integrity if integrity is not None else IntegrityConfig(),
        **cfg_over)
    ck = Checkpointer(cfg)
    for s in steps:
        p, o = _trees(scale=float(s))
        ck.save(TrainState(p, o, s, s * 8),
                manifest=({"world_size": 8, "step": s, "format": 1,
                           "plan": {"pp": 1, "vp": 1}} if manifest else None))
    ck.wait()
    return ck


class TestVerifyRoundTrip:
    def test_clean_save_verifies_ok(self, tmp_path):
        with _save_steps(tmp_path) as ck:
            v = ck.verify_step(2)
            assert v.status == "ok" and not v.failures
            assert v.groups_checked >= 5  # meta+manifest+params+2 opt groups
            assert ck.verified_latest_step() == 2
            assert ck.integrity_trail["verified_step"] == 2
            assert ck.integrity_trail["walk_back_count"] == 0

    def test_save_bf16_digests_the_cast_bytes(self, tmp_path):
        with _save_steps(tmp_path, save_bf16=True) as ck:
            assert ck.verify_step(2).status == "ok"

    def test_legacy_checkpoint_restores_with_warning(self, tmp_path, caplog):
        ck = _save_steps(tmp_path,
                         integrity=IntegrityConfig(enabled=False))
        p, o = _trees()
        assert ck.verify_step(2).status == "legacy"
        with caplog.at_level(logging.WARNING):
            restored = ck.restore(p, o, verify=True)
        assert restored.step == 2
        assert "legacy" in caplog.text.lower()
        assert ck.integrity_trail.get("legacy_restore") is True
        ck.close()

    def test_disabled_integrity_saves_no_sidecar(self, tmp_path):
        ck = _save_steps(tmp_path, integrity=IntegrityConfig(enabled=False))
        assert not (ck.directory / "2" / I.INTEGRITY_ITEM).exists()
        ck.close()

    def test_explicit_corrupt_step_raises(self, tmp_path):
        ck = _save_steps(tmp_path)
        inject_corruption(ck.directory, 2, "byte_flip")
        p, o = _trees()
        with pytest.raises(CheckpointIntegrityError, match="step 2"):
            ck.restore(p, o, step=2)
        ck.close()


# ---------------------------------------------------------------------------
# the corrupt-restore matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", I.CORRUPTION_KINDS)
class TestCorruptRestoreMatrix:
    def test_walk_back_quarantine_and_restore(self, tmp_path, kind):
        ck = _save_steps(tmp_path, steps=(1, 2, 3))
        what = inject_corruption(ck.directory, 3, kind)
        assert kind.split("_")[0] in what
        v = ck.verify_step(3)
        assert v.status == "corrupt", (kind, v)
        assert v.failures
        # walk-back: newest good step wins, the corpse is quarantined
        assert ck.verified_latest_step() == 2
        trail = ck.integrity_trail
        assert trail["verified_step"] == 2
        assert trail["walk_back_count"] == 1
        assert trail["quarantined_steps"] == [3]
        assert [e["step"] for e in I.read_ledger(ck.directory)] == [3]
        qdirs = [p.name for p in ck.directory.iterdir()
                 if I.parse_quarantine_name(p.name) == 3]
        assert len(qdirs) == 1
        # discovery agrees: orbax no longer sees step 3
        assert ck.latest_step() == 2
        # restore lands on the walked-back state
        p, o = _trees()
        restored = ck.restore(p, o)
        assert restored.step == 2
        np.testing.assert_array_equal(
            np.asarray(restored.params["w"]),
            np.full((8, 4), 2.0, np.float32))
        ck.close()


class TestGoneAndUnquarantined:
    def test_gone_step_is_skipped_not_restored(self, tmp_path, monkeypatch):
        """A step whose dir vanished between the listing and the read
        (concurrent quarantine/retention on another actor) must be walked
        past, not returned as the restore target."""
        ck = _save_steps(tmp_path, steps=(1, 2))
        real = ck.verify_step
        monkeypatch.setattr(
            ck, "verify_step",
            lambda s: (I.StepVerification(step=s, status="gone")
                       if s == 2 else real(s)))
        assert ck.verified_latest_step() == 1
        trail = ck.integrity_trail
        assert trail["verified_step"] == 1
        assert trail["walk_back_count"] == 0  # gone is not a corrupt skip
        assert trail["quarantined_steps"] == []
        ck.close()

    def test_all_gone_returns_none(self, tmp_path, monkeypatch):
        ck = _save_steps(tmp_path, steps=(1, 2))
        monkeypatch.setattr(
            ck, "verify_step",
            lambda s: I.StepVerification(step=s, status="gone"))
        assert ck.verified_latest_step() is None
        ck.close()

    def test_quarantine_off_reports_honestly(self, tmp_path):
        """quarantine: false walks past a corrupt step WITHOUT renaming or
        ledgering it — and the trail must say so, not claim a quarantine."""
        ck = _save_steps(
            tmp_path, steps=(1, 2),
            integrity=IntegrityConfig(quarantine=False))
        inject_corruption(ck.directory, 2, "byte_flip")
        assert ck.verified_latest_step() == 1
        trail = ck.integrity_trail
        assert trail["quarantined_steps"] == []
        assert trail["corrupt_steps_unquarantined"] == [2]
        assert (ck.directory / "2").exists()  # still live on disk
        assert I.read_ledger(ck.directory) == []
        ck.close()

    def test_mid_read_deletion_yields_gone_not_corrupt(self, tmp_path):
        """Retention deleting a step while the (audit) read is in flight is
        a race, not corruption — no false quarantine/ledger entry."""
        import shutil

        ck = _save_steps(tmp_path, steps=(1,))
        ck.close()

        class VanishingReader:
            def restore(self, step, args=None):
                shutil.rmtree(tmp_path / "1", ignore_errors=True)
                raise RuntimeError("read hit a half-deleted dir")

        v = I.verify_step(tmp_path, 1, mgr=VanishingReader())
        assert v.status == "gone"
        assert v.failures == []


class TestAllCorrupt:
    def test_curated_error_when_nothing_verifies(self, tmp_path):
        ck = _save_steps(tmp_path, steps=(1, 2))
        inject_corruption(ck.directory, 2, "byte_flip")
        inject_corruption(ck.directory, 1, "delete_item", item="opt_state")
        with pytest.raises(CheckpointIntegrityError) as ei:
            ck.verified_latest_step()
        msg = str(ei.value)
        assert "every retained checkpoint" in msg
        assert "step 2" in msg and "step 1" in msg
        assert I.LEDGER_NAME in msg
        assert len(ei.value.verdicts) == 2
        # both quarantined; nothing left for orbax to discover
        assert ck.latest_step() is None
        ck.close()


# ---------------------------------------------------------------------------
# quarantine naming round-trip
# ---------------------------------------------------------------------------


class TestQuarantineNaming:
    def test_parse_round_trip(self):
        name = I.quarantine_name(42, "params: content digest mismatch")
        assert name.startswith(I.QUARANTINE_PREFIX)
        assert I.parse_quarantine_name(name) == 42
        assert I.parse_quarantine_name("42") is None
        assert I.parse_quarantine_name("version_3") is None
        assert I.parse_quarantine_name("quarantined.x.y") is None

    def test_quarantined_dirs_invisible_to_discovery(self, tmp_path):
        ck = _save_steps(tmp_path, steps=(1, 2))
        inject_corruption(ck.directory, 2, "truncate")
        assert ck.verified_latest_step() == 1
        ck.close()
        # a FRESH manager (new process) sees only the good step, and the
        # ledger file + quarantine dirs don't break step discovery
        ck2 = Checkpointer(CheckpointConfig(dir=tmp_path, async_save=False))
        assert ck2.latest_step() == 1
        ck2.close()

    def test_exp_manager_version_parse_unaffected(self, tmp_path):
        from neuronx_distributed_training_tpu.trainer.exp_manager import (
            latest_version,
        )

        (tmp_path / "version_0" / "checkpoints").mkdir(parents=True)
        (tmp_path / "version_1" / "checkpoints").mkdir(parents=True)
        q = tmp_path / "version_1" / "checkpoints" / I.quarantine_name(9, "x")
        q.mkdir()
        (tmp_path / "version_1" / "checkpoints" / I.LEDGER_NAME).write_text(
            '{"entries": []}\n')
        assert latest_version(tmp_path) == 1


# ---------------------------------------------------------------------------
# post-commit save audit
# ---------------------------------------------------------------------------


class TestSaveAudit:
    def test_audit_detects_post_commit_corruption(self, tmp_path):
        ic = IntegrityConfig(audit=True, audit_deadline_seconds=30.0)
        ck = _save_steps(tmp_path, steps=(1, 2), integrity=ic)
        # bitrot lands AFTER commit; wait() kicks the audit, close() drains
        # + applies the verdicts
        inject_corruption(ck.directory, 2, "byte_flip")
        ck.wait()
        ck.close()
        trail = ck.integrity_trail
        assert trail["audit"]["audited"] == 2
        assert trail["audit"]["failed"] == 1
        assert trail["audit"]["seconds"] > 0
        assert 2 in trail.get("audit_quarantined", [])
        assert [e["step"] for e in I.read_ledger(tmp_path)] == [2]

    def test_clean_audit_quarantines_nothing(self, tmp_path):
        ic = IntegrityConfig(audit=True)
        ck = _save_steps(tmp_path, steps=(1, 2), integrity=ic)
        ck.close()
        trail = ck.integrity_trail
        assert trail["audit"] == {"audited": 2, "failed": 0,
                                  "seconds": trail["audit"]["seconds"],
                                  "incomplete": 0}
        assert trail["quarantined_steps"] == []

    def test_emergency_save_during_inflight_audit_no_deadlock(self, tmp_path):
        """Satellite: a SIGTERM grace-window emergency save landing while the
        previous step's audit is still RUNNING must neither deadlock nor
        skip a finished audit-failure quarantine — the verdict is
        snapshotted at the boundary like the stop decision."""
        release = threading.Event()
        finished_first = threading.Event()
        real_verify = I.verify_step

        def slow_verify(directory, step):
            v = real_verify(directory, step)
            if int(step) == 1:
                finished_first.set()
                release.wait(timeout=30)
            return v

        ic = IntegrityConfig(audit=True, audit_deadline_seconds=5.0)
        ck = Checkpointer(CheckpointConfig(dir=tmp_path, async_save=False,
                                           integrity=ic))
        ck._auditor._verify = slow_verify
        p, o = _trees(1.0)
        ck.save(TrainState(p, o, 1, 8))
        ck.wait()  # kicks the (slow) audit of step 1
        assert finished_first.wait(timeout=10)
        # the emergency save: drained, deadline-bounded — the audit thread
        # is parked inside its job, and this must return promptly anyway
        t0 = time.monotonic()
        p2, o2 = _trees(2.0)
        ck.save_with_retry(TrainState(p2, o2, 2, 16), force=True, drain=True,
                           deadline=time.monotonic() + 10.0)
        assert time.monotonic() - t0 < 8.0, "emergency save blocked on audit"
        release.set()
        ck.close()
        # both audits completed by the bounded teardown drain
        assert ck.integrity_trail["audit"]["audited"] == 2
        assert ck.integrity_trail["audit"]["failed"] == 0

    def test_completed_failure_verdict_applied_at_emergency_boundary(
            self, tmp_path):
        ic = IntegrityConfig(audit=True)
        ck = Checkpointer(CheckpointConfig(dir=tmp_path, async_save=False,
                                           integrity=ic))
        p, o = _trees(1.0)
        ck.save(TrainState(p, o, 1, 8))
        # corrupt AFTER commit, then let the audit finish before the
        # emergency save hits the boundary
        inject_corruption(ck.directory, 1, "byte_flip")
        ck._mgr.wait_until_finished()
        ck._kick_audits()
        assert ck._auditor.drain(timeout=20)
        # emergency save at the boundary: the snapshot applies the failed
        # verdict (quarantine) before the new save commits
        p2, o2 = _trees(2.0)
        ck.save_with_retry(TrainState(p2, o2, 2, 16), force=True, drain=True)
        assert 1 in ck.integrity_trail.get("audit_quarantined", [])
        assert ck.latest_step() == 2
        ck.close()

    def test_drain_deadline_counts_incomplete(self, tmp_path):
        hang = threading.Event()

        def never_done(directory, step):
            hang.wait(timeout=60)
            return I.StepVerification(step=step, status="ok")

        aud = I.SaveAuditor(tmp_path, verify_fn=never_done)
        aud.schedule(1)
        t0 = time.monotonic()
        assert not aud.drain(timeout=0.2)
        assert time.monotonic() - t0 < 5.0
        assert aud.stats.incomplete == 1
        hang.set()


# ---------------------------------------------------------------------------
# elastic discovery + replan key off the verified step
# ---------------------------------------------------------------------------


def _tiny_raw(tmp_path, **over):
    raw = tiny_llama_config(tmp_path, max_steps=4, save_every=2)
    raw.update(over)
    return raw


class TestElasticDiscovery:
    def test_manifest_reads_from_verified_step(self, tmp_path):
        from neuronx_distributed_training_tpu.trainer.elastic import (
            read_latest_manifest,
        )

        ck = _save_steps(tmp_path, steps=(1, 2))
        ck.close()
        inject_corruption(tmp_path, 2, "stale_sidecar")
        trail: dict = {}
        m = read_latest_manifest(tmp_path, trail=trail)
        assert m is not None and m["step"] == 1
        assert trail["verified_step"] == 1
        assert trail["walk_back_count"] == 1
        assert trail["quarantined_steps"] == [2]

    def test_all_corrupt_discovery_raises_not_silently_fresh(self, tmp_path):
        from neuronx_distributed_training_tpu.trainer.elastic import (
            read_latest_manifest,
        )

        ck = _save_steps(tmp_path, steps=(1,))
        ck.close()
        inject_corruption(tmp_path, 1, "truncate")
        with pytest.raises(CheckpointIntegrityError):
            read_latest_manifest(tmp_path)

    def test_legacy_checkpoint_discovery_warns_not_crashes(self, tmp_path,
                                                           caplog):
        from neuronx_distributed_training_tpu.trainer.elastic import (
            read_latest_manifest,
        )

        ck = _save_steps(tmp_path, steps=(1,),
                         integrity=IntegrityConfig(enabled=False))
        ck.close()
        trail: dict = {}
        with caplog.at_level(logging.WARNING):
            m = read_latest_manifest(tmp_path, trail=trail)
        assert m is not None and m["step"] == 1
        assert trail.get("legacy_restore") is True


# ---------------------------------------------------------------------------
# end-to-end: fit() resumes past a corrupt newest step
# ---------------------------------------------------------------------------


class TestFitWalkBack:
    def test_same_world_resume_walks_back_bitwise(self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.trainer.elastic import (
            discover_checkpoint_dir,
        )
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        raw = tiny_llama_config(tmp_path, max_steps=6, save_every=2)
        cfg = load_config(raw)
        t1 = Trainer.from_config(cfg, devices=devices8[:4])
        t1.fit()
        ck_dir = discover_checkpoint_dir(cfg)
        steps = sorted(int(p.name) for p in ck_dir.iterdir()
                       if p.name.isdigit())
        newest, prior = steps[-1], steps[-2]
        inject_corruption(ck_dir, newest, "byte_flip")
        # auto-resume: same world, no replan — maybe_resume's verified
        # restore must quarantine the corpse and walk back
        t2 = Trainer.from_config(load_config(raw), devices=devices8[:4])
        metrics = t2.fit()
        assert metrics and np.isfinite(metrics["loss"])
        run_dir = ck_dir.parent
        summary = json.loads((run_dir / "run_summary.json").read_text())
        trail = summary["integrity"]
        assert trail["verified_step"] == prior
        assert trail["walk_back_count"] == 1
        assert newest in trail["quarantined_steps"]
        # bitwise continuity: retrained steps equal the first run's losses
        losses = read_losses(run_dir)
        assert max(losses) == 6

    def test_corruption_drill_cross_dp(self, tmp_path, devices8):
        report = run_corruption_drill(
            tmp_path, kind="stale_sidecar", world=4, resume_world=2,
            total_steps=4, save_every=2)
        assert report["ok"]
        assert report["walked_back"] == 1
        assert report["resume_step"] == 2
        assert report["replanned"]


# ---------------------------------------------------------------------------
# data-stall watchdog
# ---------------------------------------------------------------------------


class TestDataStallWatchdog:
    def test_hung_source_raises_curated_error(self):
        hang = threading.Event()

        def hung():
            hang.wait(timeout=60)
            yield {"x": 1}

        it = PrefetchIterator(hung(), timeout_seconds=0.3)
        t0 = time.monotonic()
        with pytest.raises(DataStallError, match="data_wait_timeout_seconds"):
            next(it)
        assert time.monotonic() - t0 < 5.0
        hang.set()
        it.close()

    def test_slow_but_alive_source_never_trips(self):
        def slow():
            for i in range(3):
                time.sleep(0.05)
                yield i

        it = PrefetchIterator(slow(), timeout_seconds=2.0)
        assert list(it) == [0, 1, 2]
        it.close()

    def test_timeout_off_by_default(self):
        it = PrefetchIterator(iter([1]), timeout_seconds=0.0)
        assert it._timeout is None
        assert next(it) == 1
        it.close()

    def test_health_knob_validated(self):
        from neuronx_distributed_training_tpu.telemetry.health import (
            HealthConfig,
        )

        hc = HealthConfig.from_config({"data_wait_timeout_seconds": 30})
        assert hc.data_wait_timeout_seconds == 30.0
        with pytest.raises(ValueError, match=">= 0"):
            HealthConfig.from_config({"data_wait_timeout_seconds": -1})
        with pytest.raises(ValueError, match="data_wait_timeout_seconds"):
            HealthConfig.from_config({"data_wait_timeout_secs": 5})

    def test_loop_dumps_hang_bundle_then_raises(self, tmp_path, devices8):
        """The fit loop feeds the existing HangWatchdog bundle path on a
        data stall: hang bundle on disk, curated error out."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        raw = tiny_llama_config(tmp_path, max_steps=4, save_every=100)
        raw["exp_manager"]["telemetry"]["health"] = {
            "enabled": True, "data_wait_timeout_seconds": 0.5}
        trainer = Trainer.from_config(load_config(raw), devices=devices8[:4])

        class HungModule:
            global_batch_size = trainer.data_module.global_batch_size
            sampler = trainer.data_module.sampler
            seq_len = 32

            def sharded_batches(self, mesh):
                threading.Event().wait(timeout=60)
                yield {}

        trainer.data_module = HungModule()
        with pytest.raises(DataStallError):
            trainer.fit()
        bundles = list(trainer.exp.log_dir.glob("hang_*"))
        assert bundles, "no hang bundle written on data stall"


# ---------------------------------------------------------------------------
# offline CLI
# ---------------------------------------------------------------------------


class TestCkptVerifyCLI:
    def test_report_json_and_exit_codes(self, tmp_path, capsys):
        import ckpt_verify

        ck = _save_steps(tmp_path / "checkpoints", steps=(1, 2))
        ck.close()
        assert ckpt_verify.main([str(tmp_path), "--json", "-"]) == 0
        payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert payload["ok"] and payload["corrupt_steps"] == []
        assert [s["status"] for s in payload["steps"]] == ["ok", "ok"]

        inject_corruption(tmp_path / "checkpoints", 2, "byte_flip")
        assert ckpt_verify.main([str(tmp_path), "--json", "-"]) == 1
        payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert payload["corrupt_steps"] == [2]
        assert payload["quarantined"] == []  # report-only by default

    def test_quarantine_flag_applies_the_ledger(self, tmp_path, capsys):
        import ckpt_verify

        ck = _save_steps(tmp_path / "checkpoints", steps=(1, 2))
        ck.close()
        inject_corruption(tmp_path / "checkpoints", 2, "truncate")
        assert ckpt_verify.main(
            [str(tmp_path), "--quarantine", "--json", "-"]) == 1
        payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert payload["quarantined"] == [2]
        assert payload["ledger_entries"] == 1
        # the next resume walks straight to the good step
        ck2 = Checkpointer(CheckpointConfig(dir=tmp_path / "checkpoints",
                                            async_save=False))
        assert ck2.latest_step() == 1
        ck2.close()

    def test_single_step_and_missing(self, tmp_path, capsys):
        import ckpt_verify

        ck = _save_steps(tmp_path / "checkpoints", steps=(1,))
        ck.close()
        assert ckpt_verify.main(
            [str(tmp_path), "--step", "1", "--json", "-"]) == 0
        capsys.readouterr()
        assert ckpt_verify.main(
            [str(tmp_path), "--step", "9", "--json", "-"]) == 1
        payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert "not found" in payload["error"]

    def test_no_checkpoints_is_an_error(self, tmp_path, capsys):
        import ckpt_verify

        assert ckpt_verify.main(
            [str(tmp_path / "nowhere"), "--json", "-"]) == 1

    def test_file_path_is_a_curated_error_not_a_traceback(self, tmp_path,
                                                          capsys):
        import ckpt_verify

        f = tmp_path / "run_summary.json"
        f.write_text("{}")
        assert ckpt_verify.main([str(f), "--json", "-"]) == 1
        payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert "no checkpoint directory" in payload["error"]


# ---------------------------------------------------------------------------
# corruption injection itself
# ---------------------------------------------------------------------------


class TestInjection:
    def test_unknown_kind_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="byte_flip"):
            inject_corruption(tmp_path, 1, "bit_rot")

    def test_missing_step_rejected(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            inject_corruption(tmp_path, 7, "byte_flip")

    def test_stale_sidecar_without_older_step_tampers(self, tmp_path):
        ck = _save_steps(tmp_path, steps=(1,))
        what = inject_corruption(tmp_path, 1, "stale_sidecar")
        assert "zeroed" in what
        assert ck.verify_step(1).status == "corrupt"
        ck.close()
