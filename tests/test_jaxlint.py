"""jaxlint: every rule fires on a seeded violation, suppressions and the
ratchet baseline behave, and the package itself lints clean against the
committed baseline (the acceptance criterion)."""

import json
import textwrap
from pathlib import Path

import pytest

from neuronx_distributed_training_tpu.analysis import jaxlint
from neuronx_distributed_training_tpu.analysis.jaxlint import (
    apply_ratchet,
    fingerprint,
    lint_file,
    lint_package,
    load_baseline,
    module_is_graph,
    write_baseline,
)


def lint_snippet(tmp_path: Path, code: str, name: str = "snippet.py"):
    f = tmp_path / name
    f.write_text(textwrap.dedent(code))
    return lint_file(f, tmp_path)


GRAPH_HEADER = """
import time
import jax
import jax.numpy as jnp
import numpy as np
"""


class TestRulesFire:
    def test_jl101_item_and_float(self, tmp_path):
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def loss_fn(params, batch):
    v = float(jnp.sum(batch))
    s = batch.sum().item()
    return v + s

g = jax.grad(loss_fn)
""")
        assert sum(f.rule == "JL101" for f in rep.findings) == 2, rep.format()

    def test_jl101_asarray_on_param(self, tmp_path):
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def loss_fn(params, batch):
    host = np.asarray(batch)
    return host.sum()

g = jax.jit(loss_fn)
""")
        assert any(f.rule == "JL101" and "asarray" in f.message
                   for f in rep.findings), rep.format()

    def test_jl102_tracer_branch(self, tmp_path):
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def loss_fn(params, x):
    if jnp.any(x > 0):
        x = x + 1
    while jnp.max(x) < 3:
        x = x * 2
    return x

g = jax.grad(loss_fn)
""")
        assert sum(f.rule == "JL102" for f in rep.findings) == 2, rep.format()

    def test_jl102_static_metadata_ok(self, tmp_path):
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def loss_fn(params, x):
    if jnp.dtype(x.dtype) != jnp.dtype(jnp.float32):
        x = x.astype(jnp.float32)
    if jnp.ndim(x) == 2:
        x = x[None]
    return x

g = jax.grad(loss_fn)
""")
        assert not [f for f in rep.findings if f.rule == "JL102"], rep.format()

    def test_jl103_wall_clock(self, tmp_path):
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def step_fn(params, x):
    t0 = time.time()
    t1 = time.perf_counter()
    return x * (t1 - t0)

g = jax.jit(step_fn)
""")
        assert sum(f.rule == "JL103" for f in rep.findings) == 2, rep.format()

    def test_jl104_key_reuse(self, tmp_path):
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def sample(key):
    a = jax.random.normal(key, (2,))
    b = jax.random.uniform(key, (2,))
    return a + b
""")
        assert any(f.rule == "JL104" for f in rep.findings), rep.format()

    def test_jl104_split_and_rebind_ok(self, tmp_path):
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def sample(key):
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (2,))
    b = jax.random.uniform(k2, (2,))
    key = jax.random.fold_in(key, 1)
    c = jax.random.normal(key, (2,))
    d = jax.random.normal(jax.random.fold_in(key, 2), (2,))
    return a + b + c + d
""")
        assert not [f for f in rep.findings if f.rule == "JL104"], rep.format()

    def test_jl104_exclusive_branches_not_reuse(self, tmp_path):
        """One consumer per if/else branch: mutually exclusive, not reuse —
        but a use AFTER the branches (either path already consumed) is."""
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def sample(key, training):
    if training:
        x = jax.random.bernoulli(key, 0.5)
    else:
        x = jax.random.uniform(key)
    return x
""")
        assert not [f for f in rep.findings if f.rule == "JL104"], rep.format()
        rep2 = lint_snippet(tmp_path, GRAPH_HEADER + """
def sample(key, training):
    if training:
        x = jax.random.bernoulli(key, 0.5)
    else:
        x = jax.random.uniform(key)
    return x + jax.random.normal(key, ())
""", name="snippet2.py")
        assert sum(f.rule == "JL104" for f in rep2.findings) == 1, \
            rep2.format()

    def test_jl104_sibling_closures_independent(self, tmp_path):
        """Two nested functions each using `key` once: not reuse."""
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def build(key):
    def a():
        return jax.random.normal(key, (2,))
    def b():
        return jax.random.uniform(key, (2,))
    return a, b
""")
        assert not [f for f in rep.findings if f.rule == "JL104"], rep.format()

    def test_jl106_astype_f32(self, tmp_path):
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def loss_fn(params, batch):
    x = batch.astype(jnp.float32)
    y = jnp.astype(params, jnp.float32)
    return (x + y).sum()

g = jax.jit(loss_fn)
""")
        assert sum(f.rule == "JL106" for f in rep.findings) == 2, rep.format()

    def test_jl106_string_and_dtype_forms(self, tmp_path):
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def loss_fn(params, batch):
    a = batch.astype("float32")
    b = batch.astype(jnp.dtype("float32"))
    return (a + b).sum()

g = jax.jit(loss_fn)
""")
        assert sum(f.rule == "JL106" for f in rep.findings) == 2, rep.format()

    def test_jl106_policy_cast_ok(self, tmp_path):
        """Dtype-preserving / policy-mediated casts are not upcasts."""
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def loss_fn(params, batch, policy):
    a = batch.astype(policy.compute_dtype)
    b = batch.astype(params.dtype)
    c = batch.astype(jnp.bfloat16)
    return (a + b + c).sum()

g = jax.jit(loss_fn)
""")
        assert not [f for f in rep.findings if f.rule == "JL106"], rep.format()

    def test_jl106_host_scope_skipped(self, tmp_path):
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def report(metrics):
    return metrics.astype(jnp.float32)
""")
        assert not [f for f in rep.findings if f.rule == "JL106"], rep.format()

    def test_jl106_suppression(self, tmp_path):
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def loss_fn(params, batch):
    x = batch.astype(jnp.float32)  # jaxlint: disable=JL106
    return x.sum()

g = jax.jit(loss_fn)
""")
        assert not [f for f in rep.findings if f.rule == "JL106"], rep.format()

    def test_jl105_donated_reuse(self, tmp_path):
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def host_loop(params, opt, batch):
    step = jax.jit(lambda p, o, b: (p, o), donate_argnums=(0, 1))
    p2, o2 = step(params, opt, batch)
    print(params)
    return p2
""")
        assert any(f.rule == "JL105" and "`params`" in f.message
                   for f in rep.findings), rep.format()

    def test_jl105_rebind_ok(self, tmp_path):
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def host_loop(params, opt, batch):
    step = jax.jit(lambda p, o, b: (p, o), donate_argnums=(0, 1))
    params, opt = step(params, opt, batch)
    print(params)
    return params
""")
        assert not [f for f in rep.findings if f.rule == "JL105"], rep.format()


class TestScope:
    def test_host_module_skips_graph_rules(self, tmp_path):
        """Un-wrapped functions in a host-scope module: JL101-103 silent."""
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def boundary_fetch(metrics):
    return float(jnp.asarray(0.0)) if metrics else 0.0
""")
        assert not [f for f in rep.findings if f.rule == "JL101"], rep.format()

    def test_graph_pragma_forces_scope(self, tmp_path):
        rep = lint_snippet(tmp_path, "# jaxlint: graph\n" + GRAPH_HEADER + """
def helper(x):
    return x.sum().item()
""")
        assert any(f.rule == "JL101" for f in rep.findings), rep.format()

    def test_module_path_scope(self):
        assert module_is_graph("models/llama.py", "")
        assert module_is_graph("trainer/step.py", "")
        assert not module_is_graph("trainer/loop.py", "")
        assert not module_is_graph("data/loader.py", "")
        assert module_is_graph("data/loader.py", "# jaxlint: graph\n")


class TestSuppression:
    def test_line_suppression(self, tmp_path):
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def loss_fn(params, x):
    a = x.sum().item()  # jaxlint: disable=JL101
    b = x.sum().item()
    return a + b

g = jax.grad(loss_fn)
""")
        assert sum(f.rule == "JL101" for f in rep.findings) == 1, rep.format()

    def test_previous_line_suppression(self, tmp_path):
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def loss_fn(params, x):
    # jaxlint: disable=JL101
    a = x.sum().item()
    return a

g = jax.grad(loss_fn)
""")
        assert not [f for f in rep.findings if f.rule == "JL101"], rep.format()


class TestRatchet:
    def _one_finding_report(self, tmp_path):
        return lint_snippet(tmp_path, GRAPH_HEADER + """
def loss_fn(params, x):
    return x.sum().item()

g = jax.grad(loss_fn)
""")

    def test_baselined_finding_passes(self, tmp_path):
        rep = self._one_finding_report(tmp_path)
        baseline = [fingerprint(f) for f in rep.findings]
        fresh, stale = apply_ratchet(rep, baseline)
        assert not fresh.findings and not stale
        assert fresh.stats["baselined"] == 1

    def test_new_finding_escalates_to_error(self, tmp_path):
        rep = self._one_finding_report(tmp_path)
        fresh, stale = apply_ratchet(rep, [])
        assert fresh.findings and fresh.findings[0].severity == "error"
        assert fresh.failed("error")

    def test_stale_baseline_entry_reported(self, tmp_path):
        rep = self._one_finding_report(tmp_path)
        baseline = [fingerprint(f) for f in rep.findings] + [
            "JL101|gone.py|removed_long_ago()"]
        fresh, stale = apply_ratchet(rep, baseline)
        assert stale == ["JL101|gone.py|removed_long_ago()"]

    def test_fingerprint_stable_across_line_moves(self, tmp_path):
        rep1 = self._one_finding_report(tmp_path)
        rep2 = lint_snippet(tmp_path, "\n\n\n" + GRAPH_HEADER + """
def loss_fn(params, x):
    return x.sum().item()

g = jax.grad(loss_fn)
""", name="snippet2.py")
        fp1 = fingerprint(rep1.findings[0]).split("|", 1)[1].split("|", 1)[1]
        fp2 = fingerprint(rep2.findings[0]).split("|", 1)[1].split("|", 1)[1]
        assert fp1 == fp2  # same snippet despite the line shift

    def test_write_and_load_roundtrip(self, tmp_path):
        rep = self._one_finding_report(tmp_path)
        path = tmp_path / "baseline.json"
        write_baseline(rep, path)
        assert load_baseline(path) == sorted(
            fingerprint(f) for f in rep.findings)
        assert json.loads(path.read_text())["findings"]

    def test_write_baseline_sorted_and_deduplicated(self, tmp_path):
        """Repeated identical snippets share one line-number-free
        fingerprint: the baseline stores it ONCE, sorted, and a rewrite over
        an unchanged tree is byte-identical."""
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def loss_fn(params, x):
    print(x.sum().item())
    print(x.sum().item())
    return x

g = jax.grad(loss_fn)
""")
        assert len(rep.findings) == 2
        fps = [fingerprint(f) for f in rep.findings]
        assert fps[0] == fps[1]  # identical snippet -> identical fingerprint
        path = tmp_path / "baseline.json"
        write_baseline(rep, path)
        entries = load_baseline(path)
        assert entries == sorted(set(fps)) and len(entries) == 1
        first = path.read_bytes()
        write_baseline(rep, path)
        assert path.read_bytes() == first  # rerun is byte-stable

    def test_deduplicated_entry_matches_every_duplicate(self, tmp_path):
        """Set semantics: both findings of a duplicated snippet match the
        single baseline entry — no fresh finding, no stale entry."""
        rep = lint_snippet(tmp_path, GRAPH_HEADER + """
def loss_fn(params, x):
    print(x.sum().item())
    print(x.sum().item())
    return x

g = jax.grad(loss_fn)
""")
        path = tmp_path / "baseline.json"
        write_baseline(rep, path)
        fresh, stale = apply_ratchet(rep, load_baseline(path))
        assert not fresh.findings and not stale
        assert fresh.stats["baselined"] == 2


def test_package_lints_clean_against_committed_baseline():
    """The acceptance criterion: zero non-baselined findings on the package
    source, and zero stale entries in the committed baseline."""
    full = lint_package()
    fresh, stale = apply_ratchet(full, load_baseline())
    assert not fresh.findings, fresh.format()
    assert not stale, f"stale baseline entries: {stale}"


def test_unparseable_file_is_error(tmp_path):
    rep = lint_snippet(tmp_path, "def broken(:\n")
    assert any(f.rule == "JL000" and f.severity == "error"
               for f in rep.findings)
