"""Cluster detection (train_setup.sh equivalent): pure-env parsing."""

import os

import pytest

from neuronx_distributed_training_tpu.utils.launch import (
    ClusterSpec,
    detect_cluster,
    expand_first_host,
    restart_log_dir,
)


class TestExpandFirstHost:
    def test_plain(self):
        assert expand_first_host("node7") == "node7"

    def test_comma_list(self):
        assert expand_first_host("a1,b2,c3") == "a1"

    def test_bracket_range(self):
        assert expand_first_host("node[3-17,20]") == "node3"

    def test_zero_padding_preserved(self):
        assert expand_first_host("trn-[003-017]") == "trn-003"

    def test_bracket_single(self):
        assert expand_first_host("gpu[12]") == "gpu12"


class TestDetectCluster:
    def test_single_process_default(self):
        spec = detect_cluster({})
        assert spec.managed_by == "single"
        assert not spec.is_multiprocess

    def test_explicit_nxdt_triple_wins(self):
        spec = detect_cluster({
            "NXDT_COORDINATOR": "10.0.0.1:9999",
            "NXDT_NUM_PROCESSES": "4",
            "NXDT_PROCESS_ID": "2",
            "SLURM_NTASKS": "8",  # would otherwise pick slurm
        })
        assert spec == ClusterSpec("10.0.0.1:9999", 4, 2, "nxdt-env")

    def test_slurm(self):
        spec = detect_cluster({
            "SLURM_NTASKS": "16",
            "SLURM_PROCID": "5",
            "SLURM_STEP_NODELIST": "trn[001-004]",
            "SLURM_RESTART_COUNT": "2",
        })
        assert spec.managed_by == "slurm"
        assert spec.coordinator_address == "trn001:8476"
        assert spec.num_processes == 16
        assert spec.process_id == 5
        assert spec.restart_count == 2

    def test_slurm_without_nodelist_raises(self):
        with pytest.raises(RuntimeError, match="NODELIST"):
            detect_cluster({"SLURM_NTASKS": "2"})

    def test_ompi_with_master_addr(self):
        spec = detect_cluster({
            "OMPI_COMM_WORLD_SIZE": "8",
            "OMPI_COMM_WORLD_RANK": "3",
            "MASTER_ADDR": "head.cluster.local",
            "MASTER_PORT": "1234",
        })
        assert spec.managed_by == "ompi"
        assert spec.coordinator_address == "head.cluster.local:1234"
        assert spec.process_id == 3

    def test_ompi_without_master_falls_back_to_auto(self):
        """Plain mpirun (no MASTER_ADDR): defer to jax's own OMPI plugin."""
        spec = detect_cluster({"OMPI_COMM_WORLD_SIZE": "4",
                               "OMPI_COMM_WORLD_RANK": "1"})
        assert spec.managed_by == "ompi-auto"
        assert spec.coordinator_address == ""
        assert spec.is_multiprocess and spec.process_id == 1

    def test_single_task_slurm_is_single(self):
        assert detect_cluster({"SLURM_NTASKS": "1"}).managed_by == "single"


class TestRestartLogDir:
    def test_no_restart(self):
        assert restart_log_dir("/logs", {}) == "/logs"

    def test_restart_count(self):
        assert restart_log_dir("/logs", {"SLURM_RESTART_COUNT": "3"}) == "/logs/restart_3"


@pytest.mark.slow
def test_two_process_rendezvous_and_fit():
    """SURVEY §4 plan (b): a REAL 2-process jax.distributed rendezvous (CPU
    loopback) through utils.launch.initialize_distributed, a global 8-device
    mesh spanning both processes, and two jitted train steps whose grad
    all-reduces cross the inter-process channel.  Both ranks must see the
    same loss and final param sum (SPMD determinism).

    Phase 2 (in the same workers): the identical workload on a mesh laid out
    by ``mesh.dcn_split`` with process == DCN slice — the ``data`` axis's
    outer factor spans the two processes (gradient all-reduce crosses the
    DCN-class link) while every TP group stays inside one process.  Ranks
    must agree exactly, and the result must match the flat-mesh phase to
    reduction-order tolerance (same global math, different placement) —
    reference multi-node path ``examples/train_setup.sh:8-67``."""
    import socket
    import subprocess
    import sys
    from pathlib import Path

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker = Path(__file__).parent / "_multihost_worker.py"
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update({
            "NXDT_COORDINATOR": f"127.0.0.1:{port}",
            "NXDT_NUM_PROCESSES": "2",
            "NXDT_PROCESS_ID": str(rank),
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
            "JAX_PLATFORMS": "cpu",
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(worker)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
        assert f"MULTIHOST_OK {rank}" in out, out[-2000:]
    # SPMD: both processes computed the identical global result
    def grab(out, key):
        return [l for l in out.splitlines() if l.startswith(key)][0]

    assert grab(outs[0], "LOSS") == grab(outs[1], "LOSS")
    assert grab(outs[0], "PARAMSUM") == grab(outs[1], "PARAMSUM")
    # phase 2: dcn_split mesh — data axis spanning the processes
    for out in outs:
        assert "DCN_SPAN_OK" in out, out[-2000:]
    assert grab(outs[0], "LOSS2") == grab(outs[1], "LOSS2")
    assert grab(outs[0], "PARAMSUM2") == grab(outs[1], "PARAMSUM2")
    # same global math on a permuted placement: agreement to
    # reduction-order tolerance pins the cross-process grad all-reduce
    l1 = float(grab(outs[0], "LOSS ").split()[1])
    l2 = float(grab(outs[0], "LOSS2").split()[1])
    assert abs(l1 - l2) < 1e-5, (l1, l2)
    s1 = float(grab(outs[0], "PARAMSUM ").split()[1])
    s2 = float(grab(outs[0], "PARAMSUM2").split()[1])
    assert abs(s1 - s2) < 1e-3, (s1, s2)
