import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.models import llama
from neuronx_distributed_training_tpu.optim.adamw import (
    AdamWConfig,
    init_opt_state,
    opt_state_specs,
)
from neuronx_distributed_training_tpu.optim.lr import build_lr_schedule
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
from neuronx_distributed_training_tpu.trainer.step import jit_train_step, make_train_step
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

TINY = llama.LlamaConfig(
    vocab_size=256,
    hidden_size=64,
    intermediate_size=128,
    num_layers=2,
    num_attention_heads=4,
    num_kv_heads=2,
    max_position_embeddings=64,
    rope_theta=10000.0,
    activations_checkpoint_granularity=None,
)

FP32 = DtypePolicy(
    param_dtype=jnp.float32, compute_dtype=jnp.float32, softmax_dtype=jnp.float32
)


def _batch(key, cfg, b=4, s=16):
    ids = jax.random.randint(key, (b, s), 0, cfg.vocab_size)
    return {"input_ids": ids, "labels": ids}


def test_forward_shapes_and_loss():
    key = jax.random.PRNGKey(0)
    params = llama.init_params(key, TINY, FP32)
    batch = _batch(jax.random.PRNGKey(1), TINY)
    loss, _ = llama.forward(params, batch, TINY, FP32)
    assert loss.shape == ()
    # random init loss should be near log(vocab)
    assert abs(float(loss) - np.log(TINY.vocab_size)) < 1.0


def test_logits_only_when_no_labels():
    params = llama.init_params(jax.random.PRNGKey(0), TINY, FP32)
    batch = {"input_ids": _batch(jax.random.PRNGKey(1), TINY)["input_ids"]}
    logits, _ = llama.forward(params, batch, TINY, FP32)
    assert logits.shape == (4, 16, TINY.vocab_size)


@pytest.mark.slow
def test_remat_granularities_same_numerics():
    key = jax.random.PRNGKey(0)
    batch = _batch(jax.random.PRNGKey(1), TINY)
    losses = {}
    for gran in (None, "selective", "full"):
        cfg = llama.LlamaConfig(
            **{**TINY.__dict__, "activations_checkpoint_granularity": gran}
        )
        params = llama.init_params(key, cfg, FP32)

        def loss_fn(p):
            return llama.forward(p, batch, cfg, FP32)[0]

        loss, grads = jax.value_and_grad(loss_fn)(params)
        losses[gran] = (float(loss), float(grads["embed"]["embedding"].sum()))
    base = losses[None]
    for gran in ("selective", "full"):
        np.testing.assert_allclose(losses[gran][0], base[0], rtol=1e-5)
        np.testing.assert_allclose(losses[gran][1], base[1], rtol=1e-4)


def test_fuse_qkv_param_count_matches_unfused():
    fused = llama.init_params(jax.random.PRNGKey(0), TINY, FP32)
    unfused_cfg = llama.LlamaConfig(**{**TINY.__dict__, "fuse_qkv": False})
    unfused = llama.init_params(jax.random.PRNGKey(0), unfused_cfg, FP32)
    n = lambda t: sum(x.size for x in jax.tree_util.tree_leaves(t))
    assert n(fused) == n(unfused)


@pytest.mark.parametrize("tp,sp", [(4, False), (4, True), (8, False)])
@pytest.mark.slow
def test_tp_matches_single_device(devices8, tp, sp):
    """Sharded forward/backward must match the unsharded numerics — the
    SURVEY.md §4 plan's core parity gate."""
    cfg = llama.LlamaConfig(**{**TINY.__dict__, "sequence_parallel": sp})
    key = jax.random.PRNGKey(0)
    params = llama.init_params(key, cfg, FP32)
    batch = _batch(jax.random.PRNGKey(1), cfg)

    def loss_fn(p, b):
        return llama.forward(p, b, cfg, FP32)[0]

    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params, batch)

    mesh = build_mesh(MeshConfig(tensor_model_parallel_size=tp, sequence_parallel=sp))
    specs = llama.param_specs(cfg)
    ns = functools.partial(NamedSharding, mesh)
    sh_params = jax.device_put(
        params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
    )
    sh_batch = jax.device_put(batch, ns(P(("data", "expert"))))
    with shd.use_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(loss_fn))(sh_params, sh_batch)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for path in (("embed", "embedding"), ("final_norm", "scale")):
        g, rg = grads, ref_grads
        for k in path:
            g, rg = g[k], rg[k]
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg), rtol=5e-4, atol=1e-5)


@pytest.mark.slow
def test_train_step_loss_decreases(devices8):
    cfg = TINY
    mesh = build_mesh(MeshConfig(tensor_model_parallel_size=2))
    policy = FP32
    params = llama.init_params(jax.random.PRNGKey(0), cfg, policy)
    opt_state = init_opt_state(params, policy)
    specs = llama.param_specs(cfg)
    opt_specs = opt_state_specs(params, specs, mesh, zero1=True, policy=policy)

    def loss_fn(p, batch, step_key):
        return llama.forward(p, batch, cfg, policy)

    step_fn = make_train_step(
        loss_fn,
        AdamWConfig(grad_clip_norm=1.0),
        build_lr_schedule({"lr": 1e-3, "sched": {"name": "constant"}}),
        policy,
        num_microbatches=2,
        log_param_norm=True,
    )
    with shd.use_mesh(mesh):
        jitted = jit_train_step(step_fn, mesh, specs, opt_specs)
        batch = _batch(jax.random.PRNGKey(7), cfg, b=8, s=16)
        losses = []
        for i in range(8):
            params, opt_state, metrics = jitted(
                params, opt_state, batch, jax.random.PRNGKey(i)
            )
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0] - 0.5, losses
        assert metrics["grad_norm"] > 0
        assert metrics["param_norm"] > 0
        assert int(opt_state["step"]) == 8


def test_zero1_specs_shard_over_dp(devices8):
    cfg = TINY
    mesh = build_mesh(MeshConfig(tensor_model_parallel_size=2))  # dp=4
    params = llama.init_params(jax.random.PRNGKey(0), cfg, FP32)
    specs = llama.param_specs(cfg)
    opt_specs = opt_state_specs(params, specs, mesh, zero1=True, policy=FP32)
    # embedding moments get dp sharding on the hidden dim
    mu_spec = opt_specs["mu"]["embed"]["embedding"]
    assert "data" in str(mu_spec)
    # param specs untouched
    assert specs["embed"]["embedding"] == P("model", None)


def test_param_specs_structure_matches_params():
    """Guard against _layer_specs drifting from _init_layer (they are two
    sources of the same knowledge — a mismatch breaks jit sharding silently)."""
    import jax
    from jax.sharding import PartitionSpec
    from neuronx_distributed_training_tpu.models import llama

    for fuse_qkv in (True, False):
        for tie in (True, False):
            cfg = llama.LlamaConfig(
                vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
                num_attention_heads=4, num_kv_heads=2, fuse_qkv=fuse_qkv,
                tie_word_embeddings=tie,
            )
            params = llama.init_params(jax.random.PRNGKey(0), cfg)
            specs = llama.param_specs(cfg)
            ps = jax.tree_util.tree_structure(params)
            ss = jax.tree_util.tree_structure(
                specs, is_leaf=lambda x: isinstance(x, PartitionSpec)
            )
            assert ps == ss, f"fuse_qkv={fuse_qkv} tie={tie}: {ps} != {ss}"


@pytest.mark.slow
def test_cp_ring_matches_single_device(devices8):
    """Context-parallel (ring attention, seq sharded over `context`) forward +
    backward must match the unsharded numerics (reference CP semantics:
    base.py:199, modeling_llama.py:484)."""
    cfg = llama.LlamaConfig(
        **{**TINY.__dict__, "attention_impl": "ring", "context_parallel": True}
    )
    ref_cfg = TINY
    key = jax.random.PRNGKey(0)
    params = llama.init_params(key, ref_cfg, FP32)
    batch = _batch(jax.random.PRNGKey(1), ref_cfg, b=2, s=32)

    def ref_loss_fn(p, b):
        return llama.forward(p, b, ref_cfg, FP32)[0]

    ref_loss, ref_grads = jax.value_and_grad(ref_loss_fn)(params, batch)

    mesh = build_mesh(MeshConfig(context_parallel_size=4))
    specs = llama.param_specs(cfg)
    ns = functools.partial(NamedSharding, mesh)
    sh_params = jax.device_put(
        params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
    )
    sh_batch = jax.device_put(batch, ns(P(("data", "expert"), "context")))

    def loss_fn(p, b):
        return llama.forward(p, b, cfg, FP32)[0]

    with mesh, shd.use_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(loss_fn))(sh_params, sh_batch)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for path in (("embed", "embedding"), ("final_norm", "scale")):
        g, rg = grads, ref_grads
        for k in path:
            g, rg = g[k], rg[k]
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg), rtol=5e-4, atol=1e-5)


@pytest.mark.slow
def test_cp_ulysses_matches_single_device(devices8):
    """Context-parallel via Ulysses all-to-all (a TPU-native extension absent
    from the reference): forward + backward must match unsharded numerics."""
    cfg = llama.LlamaConfig(
        **{**TINY.__dict__, "attention_impl": "ulysses", "context_parallel": True}
    )
    ref_cfg = TINY
    key = jax.random.PRNGKey(0)
    params = llama.init_params(key, ref_cfg, FP32)
    batch = _batch(jax.random.PRNGKey(1), ref_cfg, b=2, s=32)

    def ref_loss_fn(p, b):
        return llama.forward(p, b, ref_cfg, FP32)[0]

    ref_loss, ref_grads = jax.value_and_grad(ref_loss_fn)(params, batch)

    mesh = build_mesh(MeshConfig(context_parallel_size=4))
    specs = llama.param_specs(cfg)
    ns = functools.partial(NamedSharding, mesh)
    sh_params = jax.device_put(
        params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
    )
    sh_batch = jax.device_put(batch, ns(P(("data", "expert"), "context")))

    def loss_fn(p, b):
        return llama.forward(p, b, cfg, FP32)[0]

    with mesh, shd.use_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(loss_fn))(sh_params, sh_batch)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for path in (("embed", "embedding"), ("final_norm", "scale")):
        g, rg = grads, ref_grads
        for k in path:
            g, rg = g[k], rg[k]
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg), rtol=5e-4, atol=1e-5)


class TestAttentionMask:
    """HF input_names contract: attention_mask for padded batches
    (reference llama_model.py:94-101)."""

    def test_left_padded_matches_unpadded(self):
        import dataclasses

        from neuronx_distributed_training_tpu.models import llama as llama_mod
        from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

        fp32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                           softmax_dtype=jnp.float32)
        cfg = llama_mod.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
            num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
            activations_checkpoint_granularity=None,
        )
        params = llama_mod.init_params(jax.random.PRNGKey(0), cfg, fp32)
        ids = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 3, 64)
        ref_logits, _ = llama_mod.forward(params, {"input_ids": ids}, cfg, fp32)

        pad = 4
        padded = jnp.concatenate(
            [jnp.zeros((1, pad), ids.dtype), ids], axis=1)  # left padding
        mask = jnp.concatenate(
            [jnp.zeros((1, pad), jnp.int32), jnp.ones((1, 12), jnp.int32)], axis=1)
        out_logits, _ = llama_mod.forward(
            params, {"input_ids": padded, "attention_mask": mask}, cfg, fp32)
        np.testing.assert_allclose(
            np.asarray(out_logits[:, pad:]), np.asarray(ref_logits),
            rtol=2e-5, atol=2e-5,
        )

    def test_mask_zeroes_pad_loss(self):
        from neuronx_distributed_training_tpu.models import llama as llama_mod
        from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

        fp32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                           softmax_dtype=jnp.float32)
        cfg = llama_mod.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=1,
            num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
            activations_checkpoint_granularity=None,
        )
        params = llama_mod.init_params(jax.random.PRNGKey(0), cfg, fp32)
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 3, 64)
        mask = jnp.ones((2, 16), jnp.int32).at[:, :6].set(0)
        batch = {"input_ids": ids, "labels": ids, "attention_mask": mask}
        loss_masked, _ = llama_mod.forward(params, batch, cfg, fp32)
        # equivalent loss via explicit loss_mask
        batch2 = {"input_ids": ids, "labels": ids,
                  "attention_mask": mask, "loss_mask": mask.astype(jnp.float32)}
        loss_explicit, _ = llama_mod.forward(params, batch2, cfg, fp32)
        np.testing.assert_allclose(float(loss_masked), float(loss_explicit), rtol=1e-6)
        assert np.isfinite(float(loss_masked))


    @pytest.mark.slow
    def test_sft_masked_batch_stays_on_flash_path(self, monkeypatch):
        """fusions.flash_attention + attention_mask must run the Pallas flash
        kernel, not silently fall back to O(s^2) core attention (VERDICT r2
        item 2; reference runs NKI flash on attention_mask SFT batches,
        llama_model.py:94-101)."""
        import dataclasses

        from neuronx_distributed_training_tpu.models import llama as llama_mod
        from neuronx_distributed_training_tpu.ops import flash_attention as fa
        from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

        fp32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                           softmax_dtype=jnp.float32)
        # lane-aligned shapes so the kernel itself runs (head 128, seq 256)
        cfg = llama_mod.LlamaConfig(
            vocab_size=64, hidden_size=256, intermediate_size=512, num_layers=1,
            num_attention_heads=2, num_kv_heads=2,
            max_position_embeddings=256, attention_impl="flash",
            flash_block_q=128, flash_block_kv=128,
            activations_checkpoint_granularity=None,
        )
        assert fa.flash_tileable(256, 256, 128, 2, 2)
        calls = []
        real_flash = fa._flash_fwd

        def spy_flash(*a, **kw):
            calls.append(a[3] is not None)
            return real_flash(*a, **kw)

        monkeypatch.setattr(fa, "_flash_fwd", spy_flash)
        fa._flash.defvjp(spy_flash, fa._flash_bwd)
        try:
            params = llama_mod.init_params(jax.random.PRNGKey(0), cfg, fp32)
            ids = jax.random.randint(jax.random.PRNGKey(1), (2, 256), 3, 64)
            mask = jnp.ones((2, 256), jnp.int32).at[0, 200:].set(0)
            batch = {"input_ids": ids, "labels": ids, "attention_mask": mask,
                     "loss_mask": mask.astype(jnp.float32)}

            loss, grads = jax.value_and_grad(
                lambda p: llama_mod.forward(p, batch, cfg, fp32)[0]
            )(params)
            assert np.isfinite(float(loss))
            assert calls and all(calls), (
                f"flash kernel not taken (or mask dropped): {calls}"
            )
            # numerics: must match the core path with the same mask
            core_cfg = dataclasses.replace(cfg, attention_impl="core")
            loss_core = llama_mod.forward(params, batch, core_cfg, fp32)[0]
            np.testing.assert_allclose(float(loss), float(loss_core),
                                       rtol=5e-5)
        finally:
            fa._flash.defvjp(real_flash, fa._flash_bwd)
