"""Megatron mmap dataset: .bin/.idx round-trip, C++ vs numpy index parity,
GPTDataset sample assembly."""

import numpy as np
import pytest

from neuronx_distributed_training_tpu.data.megatron import (
    GPTDataset,
    IndexedDataset,
    build_doc_idx,
    build_sample_idx,
    build_shuffle_idx,
    write_indexed_dataset,
)
from neuronx_distributed_training_tpu.data.megatron.index import (
    _load_native,
    _sample_idx_numpy,
)


def make_docs(n=20, seed=0):
    rng = np.random.Generator(np.random.PCG64(seed))
    return [rng.integers(0, 1000, rng.integers(5, 40), dtype=np.int32).astype(np.int32)
            for _ in range(n)]


class TestIndexedDataset:
    def test_round_trip(self, tmp_path):
        docs = make_docs()
        write_indexed_dataset(tmp_path / "corpus", docs)
        ds = IndexedDataset(tmp_path / "corpus")
        assert len(ds) == len(docs)
        for i in (0, 7, 19):
            np.testing.assert_array_equal(ds.get(i), docs[i])
        # partial reads
        np.testing.assert_array_equal(ds.get(3, 2, 5), docs[3][2:7])

    def test_bad_magic_raises(self, tmp_path):
        (tmp_path / "x.idx").write_bytes(b"NOTMAGIC\x00" + b"\x00" * 64)
        (tmp_path / "x.bin").write_bytes(b"")
        with pytest.raises(ValueError, match="magic"):
            IndexedDataset(tmp_path / "x")


class TestSampleIndex:
    def test_cpp_matches_numpy(self):
        docs = make_docs(50, seed=3)
        doc_lens = np.array([len(d) for d in docs], np.int32)
        doc_idx = build_doc_idx(len(docs), num_epochs=4, seed=7)
        native = _load_native()
        assert native is not None, "C++ index builder must compile in this image"
        got = build_sample_idx(doc_lens, doc_idx, num_samples=40, seq_length=16)
        want = _sample_idx_numpy(doc_lens, doc_idx, 40, 16)
        np.testing.assert_array_equal(got, want)

    def test_exhaustion_truncates(self):
        doc_lens = np.array([10, 10], np.int32)
        doc_idx = np.array([0, 1], np.int32)
        out = build_sample_idx(doc_lens, doc_idx, num_samples=100, seq_length=8)
        assert len(out) < 101  # corpus ran out

    def test_shuffle_deterministic(self):
        a = build_shuffle_idx(100, seed=5)
        b = build_shuffle_idx(100, seed=5)
        np.testing.assert_array_equal(a, b)
        assert sorted(a.tolist()) == list(range(100))


class TestGPTDataset:
    def test_samples_fixed_length_and_shifted(self, tmp_path):
        docs = make_docs(30, seed=1)
        write_indexed_dataset(tmp_path / "corpus", docs)
        ds = GPTDataset(tmp_path / "corpus", seq_length=32, num_samples=16, seed=9)
        assert len(ds) == 16
        s = ds[0]
        assert s["input_ids"].shape == (32,)
        assert s["labels"].shape == (32,)
        # labels are input shifted by one within the token stream
        s2 = ds[5]
        np.testing.assert_array_equal(s2["input_ids"][1:], s2["labels"][:-1])

    def test_cache_reused(self, tmp_path):
        docs = make_docs(30, seed=1)
        write_indexed_dataset(tmp_path / "corpus", docs)
        ds1 = GPTDataset(tmp_path / "corpus", seq_length=16, num_samples=8, seed=2)
        first = np.asarray(ds1[3]["input_ids"]).copy()
        ds2 = GPTDataset(tmp_path / "corpus", seq_length=16, num_samples=8, seed=2)
        np.testing.assert_array_equal(np.asarray(ds2[3]["input_ids"]), first)


class TestBlendedDataModule:
    """Weighted multi-corpus blend (reference MemoryEfficientBlendableDataset)."""

    def _two_corpora(self, tmp_path):
        rng = np.random.Generator(np.random.PCG64(1))
        docs_a = [np.full(30, 7, np.int32) for _ in range(10)]   # corpus A: token 7
        docs_b = [np.full(30, 9, np.int32) for _ in range(10)]   # corpus B: token 9
        write_indexed_dataset(tmp_path / "a", docs_a)
        write_indexed_dataset(tmp_path / "b", docs_b)
        return str(tmp_path / "a"), str(tmp_path / "b")

    def test_blend_ratio_and_determinism(self, tmp_path):
        from neuronx_distributed_training_tpu.data.modules import (
            BlendedMegatronDataModule,
        )

        pa, pb = self._two_corpora(tmp_path)
        dm = BlendedMegatronDataModule(
            [(0.75, pa), (0.25, pb)], seq_length=16, global_batch_size=8,
            num_samples=400, seed=3,
        )
        rows = dm.fetch_rows(np.arange(128))
        frac_a = float(np.mean(rows["input_ids"] == 7))
        assert 0.6 < frac_a < 0.9  # ~75% from corpus A
        assert dm.labels_pre_shifted
        # deterministic across rebuilds (resume safety)
        dm2 = BlendedMegatronDataModule(
            [(0.75, pa), (0.25, pb)], seq_length=16, global_batch_size=8,
            num_samples=400, seed=3,
        )
        np.testing.assert_array_equal(dm.choices, dm2.choices)
        rows2 = dm2.fetch_rows(np.arange(128))
        np.testing.assert_array_equal(rows["input_ids"], rows2["input_ids"])

    def test_build_data_module_dispatches_blend(self, tmp_path):
        from neuronx_distributed_training_tpu.data.build import build_data_module
        from neuronx_distributed_training_tpu.data.modules import (
            BlendedMegatronDataModule,
        )

        pa, pb = self._two_corpora(tmp_path)
        cfg = {
            "trainer": {"max_steps": 10},
            "data": {"seq_length": 16, "data_prefix": [0.5, pa, 0.5, pb],
                     "global_batch_size": 8},
        }
        train, val = build_data_module(cfg, {"global_batch_size": 8,
                                             "num_microbatches": 1})
        assert isinstance(train, BlendedMegatronDataModule)

    def test_odd_pairs_raise(self, tmp_path):
        from neuronx_distributed_training_tpu.data.build import build_data_module

        pa, _ = self._two_corpora(tmp_path)
        cfg = {"trainer": {"max_steps": 10},
               "data": {"seq_length": 16, "data_prefix": [0.5, pa, 0.5],
                        "global_batch_size": 8}}
        with pytest.raises(ValueError, match="pairs"):
            build_data_module(cfg, {"global_batch_size": 8, "num_microbatches": 1})
