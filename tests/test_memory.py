"""Memory observability (telemetry.memory): pprof parsing + attribution,
knob validation, boundary sampling, the OOM drill, PC501/PC502, planner
HBM calibration, and the live tiny-llama fit() smoke.

Run ``python tests/test_memory.py --regen-fixture`` to regenerate the
committed pprof fixture after changing the generator below — the
``test_fixture_committed_and_current`` ratchet fails otherwise.
"""

from __future__ import annotations

import gzip
import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax

from neuronx_distributed_training_tpu.telemetry.memory import (
    MemoryConfig,
    MemoryPlane,
    attribute_profile,
    device_memory_samples,
    is_oom_error,
    load_memory_summary,
    memory_metrics,
    parse_memory_profile,
    tree_bytes_by_subsystem,
)

FIXTURE = Path(__file__).parent / "data" / "memory_profile_fixture.pprof"


# ---------------------------------------------------------------------------
# a tiny pprof ENCODER (protobuf wire format, stdlib-only) — the fixture
# generator, and the per-test profile builder
# ---------------------------------------------------------------------------


def _enc_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_varint(field: int, value: int) -> bytes:
    return _enc_varint(field << 3) + _enc_varint(value)


def _field_bytes(field: int, payload: bytes) -> bytes:
    return _enc_varint((field << 3) | 2) + _enc_varint(len(payload)) + payload


def _packed(field: int, values: list[int]) -> bytes:
    return _field_bytes(field, b"".join(_enc_varint(v) for v in values))


class PprofBuilder:
    """Build a pprof Profile protobuf the way jax's memory profiler does:
    sample_type [(allocations, count), (space, bytes)], packed sample
    values, leaf-first location chains, kind/device labels."""

    def __init__(self) -> None:
        self.strings: list[str] = [""]
        self._functions: dict[tuple[str, str], int] = {}
        self._locations: dict[tuple[int, ...], int] = {}
        self.samples: list[bytes] = []

    def sid(self, s: str) -> int:
        try:
            return self.strings.index(s)
        except ValueError:
            self.strings.append(s)
            return len(self.strings) - 1

    def func(self, name: str, filename: str = "test.py") -> int:
        key = (name, filename)
        if key not in self._functions:
            self._functions[key] = len(self._functions) + 1
        return self._functions[key]

    def loc(self, frames: list[tuple[str, str]]) -> int:
        fids = tuple(self.func(n, f) for n, f in frames)
        if fids not in self._locations:
            self._locations[fids] = len(self._locations) + 1
        return self._locations[fids]

    def add(self, nbytes: int, count: int, stack: list, *,
            kind: str = "buffer", device: str = "TPU_0") -> None:
        """``stack``: leaf-first ``[(fn, filename), ...]`` (a bare str means
        filename "test.py")."""
        frames = [(s, "test.py") if isinstance(s, str) else tuple(s)
                  for s in stack]
        loc_ids = [self.loc([fr]) for fr in frames]
        labels = b""
        for key, val in (("kind", kind), ("device", device)):
            if val is not None:
                labels += _field_bytes(3, _field_varint(1, self.sid(key))
                                       + _field_varint(2, self.sid(val)))
        self.samples.append(
            _packed(1, loc_ids) + _packed(2, [count, nbytes]) + labels)

    def build(self, *, gzipped: bool = True) -> bytes:
        out = b""
        for t, u in (("allocations", "count"), ("space", "bytes")):
            out += _field_bytes(1, _field_varint(1, self.sid(t))
                                + _field_varint(2, self.sid(u)))
        for s in self.samples:
            out += _field_bytes(2, s)
        for fids, lid in self._locations.items():
            body = _field_varint(1, lid)
            for fid in fids:
                body += _field_bytes(4, _field_varint(1, fid))
            out += _field_bytes(4, body)
        for (name, filename), fid in self._functions.items():
            out += _field_bytes(5, _field_varint(1, fid)
                                + _field_varint(2, self.sid(name))
                                + _field_varint(4, self.sid(filename)))
        for s in self.strings:
            out += _field_bytes(6, s.encode())
        return gzip.compress(out, 9, mtime=0) if gzipped else out


def build_fixture_bytes() -> bytes:
    """The committed fixture: two devices, every attribution class, a
    dispatch pool, and an unattributed mystery — all totals hand-checkable:

    ===========  ======  ======  =========================================
    class        TPU_0   TPU_1   stack / label
    ===========  ======  ======  =========================================
    params        1000    1000   init_params
    opt_state     2000    2000   init_opt_state
    chunk_store    500       -   stage_loop @ parallel/pipeline.py
    moe_workspace    -     300   moe_dropless
    batch          100     100   _batched_device_put_impl
    (dispatch)    4000    3600   cache_miss <- <module>   [-> activations]
    executable     700       -   kind=executable
    unattributed   250       -   mystery_allocator
    ===========  ======  ======  =========================================

    Totals: TPU_0 = 8550, TPU_1 = 7000, all = 15550.
    """
    b = PprofBuilder()
    for dev, nbytes in (("TPU_0", 1000), ("TPU_1", 1000)):
        b.add(nbytes, 2, ["broadcast", "init_params", "cache_miss"],
              device=dev)
    for dev, nbytes in (("TPU_0", 2000), ("TPU_1", 2000)):
        b.add(nbytes, 3, ["zeros", "init_opt_state", "cache_miss"],
              device=dev)
    b.add(500, 1, [("stage_loop",
                    "neuronx_distributed_training_tpu/parallel/pipeline.py")],
          device="TPU_0")
    b.add(300, 1, ["moe_dropless"], device="TPU_1")
    b.add(100, 1, ["_batched_device_put_impl"], device="TPU_0")
    b.add(100, 1, ["_batched_device_put_impl"], device="TPU_1")
    b.add(4000, 8, ["cache_miss", "<module>"], device="TPU_0")
    b.add(3600, 7, ["cache_miss", "<module>"], device="TPU_1")
    b.add(700, 1, ["compile"], kind="executable", device="TPU_0")
    b.add(250, 1, ["mystery_allocator"], device="TPU_0")
    return b.build()


#: the fixture's hand-computed invariants
FIXTURE_TOTAL = 15550
FIXTURE_BY_DEVICE = {"TPU_0": 8550, "TPU_1": 7000}
FIXTURE_ATTRIBUTION_NO_HINTS = {
    "params": 2000, "opt_state": 4000, "chunk_store": 500,
    "moe_workspace": 300, "batch": 200, "activations": 7600,
    "executable": 700, "unattributed": 250,
}


# ---------------------------------------------------------------------------
# parsing + attribution
# ---------------------------------------------------------------------------


class TestParsePprof:
    def test_fixture_committed_and_current(self):
        """The ratchet: the committed fixture must match the generator —
        regenerate with ``python tests/test_memory.py --regen-fixture``."""
        assert FIXTURE.exists(), \
            "fixture missing: python tests/test_memory.py --regen-fixture"
        assert FIXTURE.read_bytes() == build_fixture_bytes()

    def test_totals_and_devices(self):
        prof = parse_memory_profile(FIXTURE.read_bytes())
        assert prof["total_bytes"] == FIXTURE_TOTAL
        assert prof["by_device"] == FIXTURE_BY_DEVICE

    def test_gzip_and_raw_parse_identically(self):
        b = PprofBuilder()
        b.add(123, 1, ["f"])
        raw = b.build(gzipped=False)
        gz = gzip.compress(raw)
        assert parse_memory_profile(raw) == parse_memory_profile(gz)

    def test_stack_and_labels(self):
        prof = parse_memory_profile(FIXTURE.read_bytes())
        execs = [s for s in prof["samples"]
                 if s["labels"].get("kind") == "executable"]
        assert len(execs) == 1 and execs[0]["bytes"] == 700
        params = [s for s in prof["samples"] if "init_params" in s["stack"]]
        assert len(params) == 2
        assert all(s["labels"]["device"] in ("TPU_0", "TPU_1")
                   for s in prof["samples"])

    def test_value_columns_selected_by_name(self):
        # swap the sample_type order: bytes first, count second — the
        # parser must follow the names, not the conventional positions
        b = PprofBuilder()
        b.sid("space"), b.sid("bytes"), b.sid("allocations"), b.sid("count")
        body = b""
        for t, u in (("space", "bytes"), ("allocations", "count")):
            body += _field_bytes(1, _field_varint(1, b.sid(t))
                                 + _field_varint(2, b.sid(u)))
        lid = b.loc([("f", "test.py")])
        body += _field_bytes(2, _packed(1, [lid]) + _packed(2, [999, 4]))
        for fids, loc_id in b._locations.items():
            lb = _field_varint(1, loc_id)
            for fid in fids:
                lb += _field_bytes(4, _field_varint(1, fid))
            body += _field_bytes(4, lb)
        for (name, filename), fid in b._functions.items():
            body += _field_bytes(5, _field_varint(1, fid)
                                 + _field_varint(2, b.sid(name))
                                 + _field_varint(4, b.sid(filename)))
        for s in b.strings:
            body += _field_bytes(6, s.encode())
        prof = parse_memory_profile(body)
        assert prof["total_bytes"] == 999
        assert prof["total_count"] == 4

    def test_live_cpu_profile_parses(self):
        import jax.numpy as jnp

        keep = jnp.ones((64, 64))  # noqa: F841 — a live buffer to find
        prof = parse_memory_profile(jax.profiler.device_memory_profile())
        assert prof["total_bytes"] > 0
        assert prof["samples"]
        att = attribute_profile(prof)
        assert sum(r["bytes"] for r in att.values()) == prof["total_bytes"]


class TestAttribution:
    def test_fixture_attribution_no_hints(self):
        prof = parse_memory_profile(FIXTURE.read_bytes())
        att = attribute_profile(prof)
        got = {cls: rec["bytes"] for cls, rec in att.items()
               if rec["bytes"]}
        assert got == FIXTURE_ATTRIBUTION_NO_HINTS

    def test_partition_reconciles_exactly(self):
        prof = parse_memory_profile(FIXTURE.read_bytes())
        att = attribute_profile(prof)
        assert sum(r["bytes"] for r in att.values()) == FIXTURE_TOTAL
        assert sum(r["count"] for r in att.values()) == prof["total_count"]

    def test_tree_join_carves_dispatch_pool(self):
        """The donation-erased dispatch pool splits by the EXACT tree
        sizes: params tops up 2000->2500, opt_state 4000->9000, master
        takes 1000, and what's left (1100) is honest activations."""
        prof = parse_memory_profile(FIXTURE.read_bytes())
        att = attribute_profile(prof, {"params": 2500, "opt_state": 9000,
                                       "master": 1000})
        assert att["params"]["bytes"] == 2500
        assert att["opt_state"]["bytes"] == 9000
        assert att["master"]["bytes"] == 1000
        assert att["activations"]["bytes"] == 7600 - 500 - 5000 - 1000
        assert sum(r["bytes"] for r in att.values()) == FIXTURE_TOTAL

    def test_tree_join_never_goes_negative(self):
        # hints larger than the pool: carve caps at the pool, the total
        # still reconciles (nothing is invented)
        prof = parse_memory_profile(FIXTURE.read_bytes())
        att = attribute_profile(prof, {"params": 10**9})
        assert sum(r["bytes"] for r in att.values()) == FIXTURE_TOTAL
        assert att["activations"]["bytes"] == 0

    def test_unattributed_never_dropped(self):
        prof = parse_memory_profile(FIXTURE.read_bytes())
        att = attribute_profile(prof, {"params": 10**9})
        assert att["unattributed"]["bytes"] == 250


# ---------------------------------------------------------------------------
# allocator sampling + metrics
# ---------------------------------------------------------------------------


class _FakeDev:
    def __init__(self, i, in_use, peak=None, limit=None, fail=False):
        self.id = i
        self.device_kind = "fake"
        self._stats = {"bytes_in_use": in_use}
        if peak is not None:
            self._stats["peak_bytes_in_use"] = peak
        if limit is not None:
            self._stats["bytes_limit"] = limit
        self._fail = fail

    def memory_stats(self):
        if self._fail:
            raise RuntimeError("no stats")
        return self._stats


class TestSampling:
    def test_samples_skip_unimplemented(self):
        devs = [_FakeDev(0, 100), _FakeDev(1, 0, fail=True)]
        s = device_memory_samples(devs)
        assert [d["device"] for d in s] == ["0"]

    def test_metrics_spread_and_peak_device(self):
        devs = [_FakeDev(0, 100, peak=150, limit=1000),
                _FakeDev(1, 900, peak=950, limit=1000),
                _FakeDev(2, 400, peak=500, limit=1000)]
        m = memory_metrics(device_memory_samples(devs))
        assert m["memory/bytes_in_use_max"] == 900
        assert m["memory/bytes_in_use_min"] == 100
        assert m["memory/bytes_in_use_p50"] == 400
        assert m["memory/peak_bytes_max"] == 950
        assert m["memory/peak_device"] == 1.0
        # headroom is the WORST device's: 1 - 900/1000
        assert m["memory/hbm_headroom_fraction"] == pytest.approx(0.1)

    def test_metrics_empty_without_stats(self):
        assert memory_metrics([]) == {}
        assert device_memory_samples(jax.devices()[:1]) == []  # CPU: None

    def test_loop_device_memory_metrics_multi_device(self, cpu_mesh,
                                                     monkeypatch):
        """The satellite: _device_memory_metrics must cover every local
        device (max/min/p50 + the named peak device), not just flat[0]."""
        from neuronx_distributed_training_tpu.trainer import loop as L

        fakes = [_FakeDev(i, 100 * (i + 1), peak=200 * (i + 1), limit=10000)
                 for i in range(4)]
        monkeypatch.setattr(L, "_local_mesh_devices", lambda mesh: fakes)
        m = L._device_memory_metrics(cpu_mesh)
        assert m["device_bytes_in_use"] == 400       # the WORST device
        assert m["device_bytes_in_use_min"] == 100
        assert m["device_bytes_in_use_p50"] == 300
        assert m["device_peak_bytes_in_use"] == 800
        assert m["device_peak_device"] == 3.0        # named by index
        assert m["device_bytes_limit"] == 10000


# ---------------------------------------------------------------------------
# knob validation
# ---------------------------------------------------------------------------


class TestKnobs:
    def test_defaults_disabled(self):
        cfg = MemoryConfig.from_config(None)
        assert cfg.enabled is False and cfg.profile is True

    def test_bool_form(self):
        assert MemoryConfig.from_config(True).enabled is True

    def test_unknown_key_did_you_mean(self):
        with pytest.raises(ValueError, match="did you mean"):
            MemoryConfig.from_config({"enabeld": True})

    def test_non_bool_rejected(self):
        with pytest.raises(ValueError, match="must be a boolean"):
            MemoryConfig.from_config({"profile": "yes"})

    @pytest.mark.parametrize("block, msg", [
        ({"start_step": -1}, "start_step"),
        ({"num_steps": 0}, "num_steps"),
        ({"headroom_alert_fraction": 1.5}, "headroom_alert_fraction"),
    ])
    def test_range_validation(self, block, msg):
        with pytest.raises(ValueError, match=msg):
            MemoryConfig.from_config(block)

    def test_telemetry_config_wiring(self):
        from neuronx_distributed_training_tpu.telemetry import (
            TelemetryConfig,
        )

        tc = TelemetryConfig.from_config(
            {"memory": {"enabled": True, "num_steps": 5}})
        assert tc.memory.enabled and tc.memory.num_steps == 5
        with pytest.raises(ValueError, match="memory"):
            TelemetryConfig.from_config({"memory": {"strat_step": 2}})

    def test_load_config_path(self, tmp_path):
        from neuronx_distributed_training_tpu.config.loader import (
            load_config,
        )

        cfg = load_config({
            "name": "x",
            "exp_manager": {"telemetry": {"memory": {"enabled": True}}},
            "model": {"vocab_size": 64, "hidden_size": 32,
                      "num_layers": 1, "num_attention_heads": 2},
            "data": {"seq_length": 16, "global_batch_size": 2,
                     "synthetic": True},
        })
        from neuronx_distributed_training_tpu.telemetry import (
            TelemetryConfig,
        )

        tc = TelemetryConfig.from_config(
            cfg["exp_manager"]["telemetry"])
        assert tc.memory.enabled

    def test_is_oom_error(self):
        assert is_oom_error(RuntimeError("RESOURCE_EXHAUSTED: oom"))
        assert is_oom_error(MemoryError("Out of memory allocating 1G"))
        assert not is_oom_error(ValueError("shape mismatch"))


# ---------------------------------------------------------------------------
# the plane: windowing + summary + OOM bundle (fake devices)
# ---------------------------------------------------------------------------


class TestMemoryPlane:
    def _plane(self, tmp_path, **kw):
        devs = [_FakeDev(0, 500, peak=600, limit=2000),
                _FakeDev(1, 900, peak=1000, limit=2000)]
        cfg = MemoryConfig(enabled=True, start_step=1, num_steps=2,
                           **kw.pop("cfg_kw", {}))
        return MemoryPlane(cfg, tmp_path, devices=devs, **kw), devs

    def test_window_captures_and_writes_summary(self, tmp_path):
        plane, _ = self._plane(tmp_path)
        m0 = plane.boundary(0)     # before the window
        assert not plane.profiled and "memory/bytes_in_use_max" in m0
        plane.boundary(1)          # in-window capture
        plane.boundary(2)          # in-window capture (max kept)
        assert not plane.profiled  # window still open
        plane.boundary(3)          # past the window: finalize
        assert plane.profiled
        s = json.loads((tmp_path / "memory_summary.json").read_text())
        assert s["schema"] == 1
        assert s["window"] == {"start_step": 1, "num_steps": 2}
        assert 1 <= s["profiled_step"] < 3
        total = s["profile"]["total_bytes"]
        assert sum(r["bytes"] for r in s["attribution"].values()) == total

    def test_boundary_metrics_and_running_peak(self, tmp_path):
        plane, devs = self._plane(tmp_path)
        m = plane.boundary(0)
        assert m["memory/peak_hbm_bytes"] == 1000.0
        devs[1]._stats["peak_bytes_in_use"] = 1500
        m = plane.boundary(1)
        assert m["memory/peak_hbm_bytes"] == 1500.0
        assert m["memory/hbm_headroom_fraction"] == pytest.approx(0.55)

    def test_close_finalizes_short_run(self, tmp_path):
        plane, _ = self._plane(tmp_path)
        plane.boundary(1)
        plane.close()
        assert (tmp_path / "memory_summary.json").exists()

    def test_run_summary_mirror(self, tmp_path):
        written = {}
        plane, _ = self._plane(tmp_path, write_run_summary=written.update,
                               predicted={"total": 12345.0})
        plane.boundary(1)
        plane.boundary(5)
        assert "memory" in written
        assert written["memory"]["predicted_hbm_bytes"] == 12345.0
        assert written["memory"]["attribution"]

    def test_headroom_alert_warns_once(self, tmp_path, caplog):
        plane, _ = self._plane(
            tmp_path, cfg_kw={"headroom_alert_fraction": 0.9})
        import logging

        with caplog.at_level(logging.WARNING,
                             logger="neuronx_distributed_training_tpu"
                                    ".telemetry.memory"):
            plane.boundary(0)
            plane.boundary(1)
        warns = [r for r in caplog.records if "headroom" in r.message]
        assert len(warns) == 1
        assert "device 1" in warns[0].getMessage()  # the WORST device named

    def test_headroom_alert_names_limit_reporting_device(self, tmp_path,
                                                         caplog):
        """A device without a bytes_limit must never be named in the
        OOM-proximity warning — only limit-reporting devices rank."""
        devs = [_FakeDev(0, 10**9),                       # no limit
                _FakeDev(1, 950, peak=960, limit=1000)]   # the real risk
        plane = MemoryPlane(
            MemoryConfig(enabled=True, headroom_alert_fraction=0.5,
                         profile=False),
            tmp_path, devices=devs)
        import logging

        with caplog.at_level(logging.WARNING,
                             logger="neuronx_distributed_training_tpu"
                                    ".telemetry.memory"):
            plane.boundary(0)
        warns = [r for r in caplog.records if "headroom" in r.message]
        assert len(warns) == 1
        assert "device 1" in warns[0].getMessage()

    def test_dump_oom_bundle_anatomy(self, tmp_path):
        written = {}
        plane, _ = self._plane(
            tmp_path, write_run_summary=written.update,
            predicted={"params": 10.0, "total": 99.0},
            run_facts={"model_family": "LlamaConfig"})
        plane.boundary(0)
        plane.boundary(1)
        bundle = plane.dump_oom(
            7, RuntimeError("RESOURCE_EXHAUSTED: oom"),
            boundary_metrics={"loss": 1.0},
            memory_analysis={"peak_bytes": 4096})
        assert bundle == tmp_path / "oom_00000007"
        doc = json.loads((bundle / "oom.json").read_text())
        assert doc["kind"] == "oom" and doc["step"] == 7
        assert "RESOURCE_EXHAUSTED" in doc["error"]
        assert doc["predicted_hbm_breakdown"]["total"] == 99.0
        assert doc["memory_analysis"]["peak_bytes"] == 4096
        assert doc["attribution_at_death"]  # fresh capture (CPU allocator)
        ring = json.loads((bundle / "samples.json").read_text())
        assert [r["step"] for r in ring] == [0, 1]
        assert written["oom"]["bundle"] == "oom_00000007"
        # at most one per process
        assert plane.dump_oom(8, RuntimeError("RESOURCE_EXHAUSTED")) is None

    def test_disabled_plane_is_inert(self, tmp_path):
        plane = MemoryPlane(MemoryConfig(), tmp_path, devices=[])
        assert plane.boundary(1) == {}
        plane.close()
        assert not (tmp_path / "memory_summary.json").exists()
        assert plane.dump_oom(1, RuntimeError("RESOURCE_EXHAUSTED")) is None


# ---------------------------------------------------------------------------
# tree bytes (exact host-side accounting)
# ---------------------------------------------------------------------------


class TestTreeBytes:
    def test_sharded_tree_accounting(self, cpu_mesh):
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        full = jax.device_put(
            jnp.zeros((8, 4), jnp.float32),
            NamedSharding(cpu_mesh, P(("data", "expert"))))
        repl = jax.device_put(jnp.zeros((4,), jnp.float32),
                              NamedSharding(cpu_mesh, P()))
        out = tree_bytes_by_subsystem(
            {"w": full}, {"mu": {"w": full}, "nu": {"w": full},
                          "master": {"w": repl}})
        # sharded [8,4] f32 over 4-way dp x 2-way tp... the ("data",
        # "expert") spec shards dim0 over data*expert=4; per-device shard
        # (2, 4) x 4B x 8 devices = 256B; replicated (4,) = 16B x 8 = 128B
        assert out["params"] == full.sharding.shard_shape((8, 4))[0] * 4 \
            * 4 * len(full.sharding.addressable_devices)
        assert out["opt_state"] == 2 * out["params"]
        assert out["master"] == 4 * 4 * 8

    def test_health_excluded_from_mu_nu(self):
        # opt_state = mu + nu + step; the health counters are forensic
        # bookkeeping, not optimizer state bytes worth calibrating against
        a = np.zeros((4,), np.float32)
        out = tree_bytes_by_subsystem(
            {"w": a}, {"mu": {"w": a}, "nu": {"w": a},
                       "health": {"c": np.zeros((), np.int32)},
                       "step": np.zeros((), np.int32)})
        assert out["opt_state"] == 16 + 16 + 4


# ---------------------------------------------------------------------------
# PC501 / PC502 fault injections (analysis.perf_contract)
# ---------------------------------------------------------------------------


def _facts(**over):
    base = {
        "version": 1,
        "workload": {"source": "bench", "device": "cpu"},
        "step_time_ms": 100.0, "mfu": 0.07, "tokens_per_sec": 5000.0,
        "achieved_overlap": None, "exposed_collective_seconds": None,
        "overlap_by_class": {}, "bubble_fraction_measured": None,
        "bubble_fraction_predicted": None, "peak_hbm_bytes": 1e9,
        "hbm_headroom_fraction": 0.5, "predicted_hbm_bytes": None,
        "residuals": None,
    }
    base.update(over)
    return base


class TestPerfContractMemory:
    def test_pc501_fires_on_peak_growth(self):
        from neuronx_distributed_training_tpu.analysis.perf_contract import (
            diff_facts,
        )

        rep = diff_facts(_facts(), _facts(peak_hbm_bytes=1.2e9))
        assert any(f.rule == "PC501" and f.severity == "error"
                   for f in rep.findings)

    def test_pc501_in_band_and_improvement(self):
        from neuronx_distributed_training_tpu.analysis.perf_contract import (
            diff_facts,
        )

        rep = diff_facts(_facts(), _facts(peak_hbm_bytes=1.05e9))
        assert not any(f.rule == "PC501" for f in rep.findings)
        rep = diff_facts(_facts(), _facts(peak_hbm_bytes=0.5e9))
        assert any(f.rule == "PC110" and "HBM" in f.message
                   for f in rep.findings)

    def test_pc501_skipped_when_either_side_missing(self):
        from neuronx_distributed_training_tpu.analysis.perf_contract import (
            diff_facts,
        )

        rep = diff_facts(_facts(peak_hbm_bytes=None), _facts())
        assert not any(f.rule == "PC501" for f in rep.findings)

    def test_pc502_baseline_independent(self):
        from neuronx_distributed_training_tpu.analysis.perf_contract import (
            check_perf,
        )

        # no baseline on disk: PC000 + the calibration gate still fires
        rep = check_perf(
            "nonexistent_topology_xyz",
            _facts(peak_hbm_bytes=2e9, predicted_hbm_bytes=1e9),
            baselines_dir=Path("/nonexistent"))
        assert any(f.rule == "PC502" and f.severity == "error"
                   for f in rep.findings)

    def test_pc502_inside_calibration_band(self):
        from neuronx_distributed_training_tpu.analysis.perf_contract import (
            AuditReport,
            DEFAULT_NOISE,
            calibration_findings,
        )

        rep = AuditReport(config="x")
        calibration_findings(
            _facts(peak_hbm_bytes=1.2e9, predicted_hbm_bytes=1e9),
            DEFAULT_NOISE, rep)
        assert not any(f.rule == "PC502" for f in rep.findings)

    def test_bench_facts_carry_memory_fields(self):
        from neuronx_distributed_training_tpu.analysis.perf_contract import (
            perf_facts_from_bench,
        )

        facts = perf_facts_from_bench({
            "metric": "m", "value": 1.0, "peak_hbm_bytes": 123.0,
            "hbm_headroom_fraction": 0.25})
        assert facts["peak_hbm_bytes"] == 123.0
        assert facts["hbm_headroom_fraction"] == 0.25


# ---------------------------------------------------------------------------
# HBM calibration (autotune.cost_model)
# ---------------------------------------------------------------------------


def _synthetic_summary(**over):
    doc = {
        "schema": 1,
        "profile": {"total_bytes": 2000, "num_devices": 2,
                    "by_device": {"TPU_0": 1000, "TPU_1": 1000}},
        "attribution": {"activations": {"bytes": 600, "count": 3},
                        "chunk_store": {"bytes": 200, "count": 1}},
        "tree_bytes": {"params": 800, "opt_state": 400},
        "sampled": {"peak_hbm_bytes": 1200},
        "predicted": {"params": 500.0, "opt_state": 100.0,
                      "activations": 600.0, "pipeline_rings": 50.0,
                      "total": 1250.0},
    }
    doc.update(over)
    return doc


class TestHbmCalibration:
    def test_ratios_hand_computed(self):
        from neuronx_distributed_training_tpu.autotune.cost_model import (
            hbm_calibration_from_memory_summary,
        )

        cal = hbm_calibration_from_memory_summary(_synthetic_summary())
        # per-device measured: params 800/2=400 vs 500 -> 0.8;
        # opt_state 400/2=200 vs 100 -> 2.0; activations 600/2=300 vs
        # 600 -> 0.5; chunk_store 200/2=100 vs pipeline_rings 50 -> 2.0;
        # total: the sampled peak is ALREADY per-device (the worst single
        # device's watermark) — 1200 vs 1250 -> 0.96, NOT /n_dev
        assert cal["params"] == pytest.approx(0.8)
        assert cal["opt_state"] == pytest.approx(2.0)
        assert cal["activations"] == pytest.approx(0.5)
        assert cal["pipeline_rings"] == pytest.approx(2.0)
        assert cal["total"] == pytest.approx(0.96)

    def test_total_falls_back_to_profile_per_device(self):
        # without allocator stats the profile's all-device total divides
        # by the device count: 2000/2=1000 vs 1250 -> 0.8 — the same
        # per-device units PC502 and the baselines use
        from neuronx_distributed_training_tpu.autotune.cost_model import (
            hbm_calibration_from_memory_summary,
        )

        cal = hbm_calibration_from_memory_summary(
            _synthetic_summary(sampled={}))
        assert cal["total"] == pytest.approx(2000 / 2 / 1250)

    def test_no_predicted_raises(self):
        from neuronx_distributed_training_tpu.autotune.cost_model import (
            hbm_calibration_from_memory_summary,
        )

        with pytest.raises(ValueError, match="calibrat"):
            hbm_calibration_from_memory_summary(
                _synthetic_summary(predicted=None))

    def test_ratios_clamped(self):
        from neuronx_distributed_training_tpu.autotune.cost_model import (
            hbm_calibration_from_memory_summary,
        )

        doc = _synthetic_summary(
            tree_bytes={"params": 10**12}, predicted={"params": 1.0})
        cal = hbm_calibration_from_memory_summary(doc)
        assert cal["params"] == 20.0  # the sanity clamp

    def test_breakdown_applies_ratios(self):
        from neuronx_distributed_training_tpu.autotune.cost_model import (
            hbm_breakdown,
        )
        from neuronx_distributed_training_tpu.autotune.space import (
            ModelFacts,
        )
        from neuronx_distributed_training_tpu.config.loader import (
            load_config,
        )

        cfg = load_config(_plan_raw_cfg())
        facts = ModelFacts.from_config(cfg)
        plan = facts.declared_plan_for(2)
        base = hbm_breakdown(facts, plan)
        cal = hbm_breakdown(facts, plan, calibration={"params": 2.0})
        assert cal["params"] == pytest.approx(2.0 * base["params"])
        assert cal["total"] == pytest.approx(
            base["total"] + base["params"])

    def test_priced_calibration_is_conservative(self):
        """Transient-category ratios floor at 1.0 in pricing (a boundary
        capture can't see freed step transients), state ratios move both
        ways, and the audit-only ``total`` is dropped."""
        from neuronx_distributed_training_tpu.autotune.cost_model import (
            priced_hbm_calibration,
        )

        priced = priced_hbm_calibration(
            {"params": 0.8, "opt_state": 2.0, "activations": 0.05,
             "pipeline_rings": 1.7, "total": 0.3})
        assert priced == {"params": 0.8, "opt_state": 2.0,
                          "activations": 1.0, "pipeline_rings": 1.7}

    def test_load_memory_summary_from_dir(self, tmp_path):
        doc = _synthetic_summary()
        (tmp_path / "memory_summary.json").write_text(json.dumps(doc))
        assert load_memory_summary(tmp_path)["sampled"] == doc["sampled"]


# ---------------------------------------------------------------------------
# live fit() integration
# ---------------------------------------------------------------------------


def _fit_cfg(tmp_path, *, memory=None, max_steps=5, extra_tel=None,
             extra_em=None):
    from neuronx_distributed_training_tpu.config.loader import load_config

    tel = {"memory": memory if memory is not None
           else {"enabled": True, "start_step": 1, "num_steps": 2}}
    tel.update(extra_tel or {})
    em = {"exp_dir": str(tmp_path / "exp"),
          "create_tensorboard_logger": False, "log_files": False,
          "telemetry": tel}
    em.update(extra_em or {})
    return load_config({
        "name": "memsmoke", "model_source": "hf", "seed": 7,
        "trainer": {"max_steps": max_steps, "log_every_n_steps": 1},
        "exp_manager": em,
        "distributed_strategy": {"tensor_model_parallel_size": 2,
                                 "sequence_parallel": True},
        "data": {"global_batch_size": 8, "micro_batch_size": 2,
                 "seq_length": 32, "synthetic": True},
        "model": {"vocab_size": 128, "hidden_size": 64,
                  "intermediate_size": 128, "num_layers": 2,
                  "num_attention_heads": 4, "num_key_value_heads": 2,
                  "max_position_embeddings": 32,
                  "optim": {"name": "adamw_fp32OptState", "lr": 1e-3}},
        "precision": {"type": "mixed_precision"},
    })


class TestLiveFit:
    def test_memory_summary_from_real_fit(self, tmp_path, devices8):
        """The acceptance bar: a live CPU tiny-llama fit() produces a
        memory_summary.json whose attribution total reconciles with the
        profile's in-use bytes, with tree bytes + the planner's predicted
        breakdown stamped alongside."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        t = Trainer.from_config(_fit_cfg(tmp_path),
                                enable_checkpointing=False)
        t.fit()
        path = Path(t.exp.log_dir) / "memory_summary.json"
        assert path.exists()
        s = json.loads(path.read_text())
        total = s["profile"]["total_bytes"]
        assert total > 0
        att = s["attribution"]
        assert sum(r["bytes"] for r in att.values()) == total
        assert "unattributed" in att or all(
            cls in ("params", "opt_state", "master", "ema", "activations",
                    "chunk_store", "moe_workspace", "batch", "executable")
            for cls in att)
        # the exact tree join: params + mu/nu carved out of the donated
        # dispatch pool by their true sizes
        tb = s["tree_bytes"]
        assert tb["params"] > 0 and tb["opt_state"] > 0
        assert att["params"]["bytes"] == tb["params"]
        assert att["opt_state"]["bytes"] == tb["opt_state"]
        # the planner's prediction rides along (predicted-vs-actual in one
        # artifact)
        assert s["predicted"] and s["predicted"]["total"] > 0
        # the run_summary mirror
        rs = json.loads(
            (Path(t.exp.log_dir) / "run_summary.json").read_text())
        assert rs["memory"]["in_use_bytes"] == total

    def test_planner_calibration_round_trip(self, tmp_path, devices8):
        """memory_summary.json from a live capture feeds plan_config:
        measured-vs-prior HBM ratios land in the PlanReport (format + dict)
        and reprice the lattice."""
        from neuronx_distributed_training_tpu.autotune import plan_config
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        t = Trainer.from_config(_fit_cfg(tmp_path),
                                enable_checkpointing=False)
        t.fit()
        path = Path(t.exp.log_dir) / "memory_summary.json"
        rep = plan_config(_plan_raw_cfg(), chips=2, audit=False,
                          calibration=str(path))
        assert rep.error is None
        assert rep.hbm_calibration
        assert "params" in rep.hbm_calibration
        assert "total" in rep.hbm_calibration
        assert "HBM calibration (measured/prior)" in rep.format()
        assert rep.to_dict()["hbm_calibration"]

    def test_oom_drill_through_fault_injector(self, tmp_path, devices8):
        """FaultInjector mode=oom at step 3: the RESOURCE_EXHAUSTED escapes
        fit(), and the complete oom_<step>/ bundle is on disk first —
        samples ring, attribution, census memory_analysis bytes, predicted
        breakdown."""
        from neuronx_distributed_training_tpu.trainer.elastic import (
            FaultInjector,
            SimulatedOOM,
        )
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        t = Trainer.from_config(_fit_cfg(tmp_path, max_steps=8),
                                enable_checkpointing=False)
        t.fault_injector = FaultInjector(at_step=3, mode="oom")
        with pytest.raises(SimulatedOOM, match="RESOURCE_EXHAUSTED"):
            t.fit()
        bundles = sorted(Path(t.exp.log_dir).glob("oom_*"))
        assert len(bundles) == 1
        doc = json.loads((bundles[0] / "oom.json").read_text())
        assert doc["kind"] == "oom"
        assert "RESOURCE_EXHAUSTED" in doc["error"]
        assert doc["attribution_at_death"]
        assert doc["tree_bytes"] is None or doc["tree_bytes"]["params"] > 0
        assert doc["predicted_hbm_breakdown"]["total"] > 0
        # the compile census ran at step 0, so its memory_analysis bytes
        # are in the bundle (predicted-vs-actual in ONE artifact)
        assert doc["memory_analysis"] and doc["memory_analysis"]["peak_bytes"] > 0
        assert (bundles[0] / "samples.json").exists()
        rs = json.loads(
            (Path(t.exp.log_dir) / "run_summary.json").read_text())
        assert rs["oom"]["step"] == 3
        json.dumps(doc, allow_nan=False)  # strict JSON

    def test_aot_once_and_dispatch_ahead_with_memory(self, tmp_path,
                                                     devices8):
        """Memory observability must add ZERO host syncs between boundaries
        and keep the AOT-once contract — the instrumented-step proof the
        fleet/control layers pin, with the memory plane on."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _fit_cfg(tmp_path, max_steps=6,
                       extra_tel={"fleet": {"enabled": True}})
        cfg["trainer"]["log_every_n_steps"] = 3
        t = Trainer.from_config(cfg, enable_checkpointing=False)

        conversions: list[int] = []

        class _Scalar:
            def __init__(self, step):
                self.step = step

            def __float__(self):
                conversions.append(self.step)
                return 1.0

        real_params, real_opt = t.params, t.opt_state

        def fake_step(params, opt_state, batch, key):
            return real_params, real_opt, {"loss": _Scalar(t.step),
                                           "grad_norm": _Scalar(t.step)}

        t.train_step = fake_step
        t.fit()
        assert conversions, "boundaries must fetch metrics"
        assert set(conversions) == {2, 5}, conversions

    def test_aot_once_with_memory_enabled(self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        t = Trainer.from_config(_fit_cfg(tmp_path, max_steps=5),
                                enable_checkpointing=False)
        t.fit()
        assert not hasattr(t.train_step, "lower")  # AOT-once held
        assert t.step == 5

    def test_run_facts_from_memory_summary_feed_perf_contract(
            self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.analysis.perf_contract import (
            perf_facts_from_run,
        )
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        t = Trainer.from_config(_fit_cfg(tmp_path),
                                enable_checkpointing=False)
        t.fit()
        facts = perf_facts_from_run(Path(t.exp.log_dir))
        # CPU reports no allocator stats, so the peak falls back to the
        # profile's worst device; predicted comes from the stamped plan
        assert facts["peak_hbm_bytes"] and facts["peak_hbm_bytes"] > 0
        assert facts["predicted_hbm_bytes"] and \
            facts["predicted_hbm_bytes"] > 0


def _plan_raw_cfg():
    """A plannable raw config matching the live-fit tiny llama (tp=2)."""
    return {
        "name": "memplan", "model_source": "hf",
        "trainer": {"max_steps": 1, "devices": 2},
        "distributed_strategy": {"tensor_model_parallel_size": 2,
                                 "zero1": True},
        "data": {"seq_length": 32, "global_batch_size": 8,
                 "micro_batch_size": 4, "synthetic": True},
        "model": {"architecture": "llama", "vocab_size": 128,
                  "hidden_size": 64, "intermediate_size": 128,
                  "num_layers": 2, "num_attention_heads": 4,
                  "num_key_value_heads": 2,
                  "max_position_embeddings": 32},
        "precision": {"type": "mixed_precision"},
    }


# ---------------------------------------------------------------------------
# report CLIs
# ---------------------------------------------------------------------------


class TestReportCLIs:
    def test_memory_report_on_fixture_json_contract(self):
        """The verify-SKILL smoke: memory_report on the committed pprof
        fixture must render the attribution table and end with a parseable
        JSON last line (the shared tools/_jsonout contract)."""
        out = subprocess.run(
            [sys.executable,
             str(Path(__file__).parent.parent / "tools" / "memory_report.py"),
             str(FIXTURE), "--json", "-"],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert "unattributed" in out.stdout
        payload = json.loads(out.stdout.strip().splitlines()[-1])
        assert payload["total_bytes"] == FIXTURE_TOTAL
        got = {cls: rec["bytes"] for cls, rec in payload["attribution"].items()
               if rec["bytes"]}
        assert got == FIXTURE_ATTRIBUTION_NO_HINTS

    def test_memory_report_on_summary_and_oom(self, tmp_path):
        doc = _synthetic_summary()
        p = tmp_path / "memory_summary.json"
        p.write_text(json.dumps(doc))
        out = subprocess.run(
            [sys.executable,
             str(Path(__file__).parent.parent / "tools" / "memory_report.py"),
             str(tmp_path), "--json", "-"],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert "predicted vs measured" in out.stdout
        assert json.loads(out.stdout.strip().splitlines()[-1])["schema"] == 1

    def test_metrics_report_renders_memory_section(self, tmp_path):
        run = tmp_path / "run"
        run.mkdir()
        (run / "run_summary.json").write_text(json.dumps({
            "memory": {"profiled_step": 2, "in_use_bytes": 1000,
                       "attribution": {"params": 600, "unattributed": 400}},
            "oom": {"step": 4, "bundle": "oom_00000004", "error": "boom"},
        }))
        (run / "metrics.jsonl").write_text(
            json.dumps({"step": 1, "loss": 1.0}) + "\n")
        out = subprocess.run(
            [sys.executable,
             str(Path(__file__).parent.parent / "tools"
                 / "metrics_report.py"), str(run)],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert "memory (telemetry.memory" in out.stdout
        assert "OOM at step 4" in out.stdout
        assert "params" in out.stdout


if __name__ == "__main__":
    if "--regen-fixture" in sys.argv:
        FIXTURE.parent.mkdir(parents=True, exist_ok=True)
        FIXTURE.write_bytes(build_fixture_bytes())
        print(f"wrote {FIXTURE} ({FIXTURE.stat().st_size} bytes)")
    else:
        raise SystemExit(pytest.main([__file__, "-q", *sys.argv[1:]]))
