import jax
import pytest

from neuronx_distributed_training_tpu.parallel.mesh import (
    AXES,
    MeshConfig,
    batch_partition_spec,
    build_mesh,
    dp_degree,
)


def test_axes_order():
    assert AXES == ("pipe", "data", "expert", "context", "model")


def test_default_mesh_is_all_data(devices8):
    mesh = build_mesh()
    assert mesh.shape["data"] == 8
    assert all(mesh.shape[a] == 1 for a in AXES if a != "data")
    assert dp_degree(mesh) == 8


@pytest.mark.parametrize(
    "tp,pp,cp,ep",
    [(2, 1, 1, 1), (4, 2, 1, 1), (2, 2, 2, 1), (2, 1, 1, 2), (8, 1, 1, 1), (1, 1, 1, 8)],
)
def test_mesh_shapes(devices8, tp, pp, cp, ep):
    cfg = MeshConfig(
        tensor_model_parallel_size=tp,
        pipeline_model_parallel_size=pp,
        context_parallel_size=cp,
        expert_model_parallel_size=ep,
    )
    mesh = build_mesh(cfg)
    assert mesh.shape["model"] == tp
    assert mesh.shape["pipe"] == pp
    assert mesh.shape["context"] == cp
    assert mesh.shape["expert"] == ep
    # dp derivation matches the reference rule world/(tp*pp*cp)
    assert cfg.dp_size(8) == 8 // (tp * pp * cp)
    assert dp_degree(mesh) == cfg.dp_size(8)


def test_invalid_mesh_rejected(devices8):
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(tensor_model_parallel_size=3))
    with pytest.raises(ValueError):
        # ep must divide dp
        build_mesh(MeshConfig(tensor_model_parallel_size=4, expert_model_parallel_size=4))
    with pytest.raises(ValueError):
        MeshConfig(sequence_parallel=True).validate(8)


def test_from_config_dict():
    cfg = MeshConfig.from_config(
        {
            "tensor_model_parallel_size": 4,
            "pipeline_model_parallel_size": 2,
            "virtual_pipeline_model_parallel_size": None,
            "zero1": True,
            "kv_replicator": 4,
        }
    )
    assert cfg.tp == 4 and cfg.pp == 2 and cfg.virtual_pipeline_model_parallel_size == 1


def test_batch_partition_spec(devices8):
    mesh = build_mesh(MeshConfig(context_parallel_size=2))
    spec = batch_partition_spec(mesh, context_sharded_seq=True)
    assert spec == jax.sharding.PartitionSpec(("data", "expert"), "context")


class TestDcnSplit:
    """Multi-slice layout: DP (else PP) over DCN, everything else over ICI."""

    def test_data_axis_preferred(self):
        from neuronx_distributed_training_tpu.parallel.mesh import AXES, dcn_split

        dims = (2, 8, 1, 1, 4)  # pipe, data, expert, context, model
        dcn, ici = dcn_split(dims, 4)
        assert dcn == (1, 4, 1, 1, 1)
        assert ici == (2, 2, 1, 1, 4)

    def test_pipe_fallback(self):
        from neuronx_distributed_training_tpu.parallel.mesh import dcn_split

        dims = (4, 3, 1, 1, 4)  # data=3 does not divide 2 slices; pipe=4 does
        dcn, ici = dcn_split(dims, 2)
        assert dcn == (2, 1, 1, 1, 1)
        assert ici == (2, 3, 1, 1, 4)

    def test_no_axis_divides(self):
        from neuronx_distributed_training_tpu.parallel.mesh import dcn_split

        assert dcn_split((3, 5, 1, 1, 4), 2) is None
