"""Mixtral: forward/loss with aux load-balancing, EP+TP sharded parity, grads."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.models import llama, mixtral
from neuronx_distributed_training_tpu.ops import moe as moe_ops
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

FP32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                   softmax_dtype=jnp.float32)

CFG = mixtral.MixtralConfig(
    llama=llama.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=2,
        num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
        activations_checkpoint_granularity=None,
    ),
    moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True,
                          router_aux_loss_coef=0.02),
)


def _batch(key, b=4, s=16):
    ids = jax.random.randint(key, (b, s), 0, CFG.llama.vocab_size)
    return {"input_ids": ids, "labels": ids}


class TestMixtralForward:
    def test_loss_and_aux(self):
        params = mixtral.init_params(jax.random.PRNGKey(0), CFG, FP32)
        loss, aux = mixtral.forward(params, _batch(jax.random.PRNGKey(1)), CFG, FP32)
        assert loss.shape == ()
        assert np.isfinite(float(loss))
        # router_aux_loss is coefficient-weighted; total = lm + aux
        np.testing.assert_allclose(
            float(loss),
            float(aux["lm_loss"]) + float(aux["router_aux_loss"]),
            rtol=1e-6,
        )
        # weighted LB loss >= coef * uniform minimum (1.0)
        assert float(aux["router_aux_loss"]) >= 0.02

    def test_grads_reach_experts_and_router(self):
        params = mixtral.init_params(jax.random.PRNGKey(0), CFG, FP32)
        batch = _batch(jax.random.PRNGKey(1))

        def loss_fn(p):
            return mixtral.forward(p, batch, CFG, FP32)[0]

        grads = jax.grad(loss_fn)(params)
        g_experts = grads["layers"]["mlp"]["experts"]["gate_up"]
        g_router = grads["layers"]["mlp"]["router"]["w"]
        assert float(jnp.abs(g_experts).sum()) > 0
        assert float(jnp.abs(g_router).sum()) > 0

    def test_dropped_mode_runs(self):
        cfg = mixtral.MixtralConfig(
            llama=CFG.llama,
            moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=False,
                                  capacity_factor=2.0),
        )
        params = mixtral.init_params(jax.random.PRNGKey(0), cfg, FP32)
        loss, _ = mixtral.forward(params, _batch(jax.random.PRNGKey(1)), cfg, FP32)
        assert np.isfinite(float(loss))

    def test_from_config_reference_schema(self):
        cfg = mixtral.MixtralConfig.from_config({
            "vocab_size": 320, "hidden_size": 64, "num_layers": 4,
            "num_attention_heads": 8, "num_key_value_heads": 2,
            "sliding_window": 128,
            "moe": {"num_experts": 8, "top_k": 2, "dropless": True},
        })
        assert cfg.moe.num_experts == 8
        assert cfg.llama.sliding_window == 128
        assert cfg.moe.capacity_factor is None


class TestMixtralSharded:
    def test_ep_tp_parity(self, devices8):
        """EP=2 x TP=2 x DP=2 sharded loss/grads match unsharded.

        Regression pin for the ragged_dot EP hazard: XLA's SPMD partitioner
        has no rule for ragged_dot's GROUP dimension — with the expert dim
        sharded on a strided mesh axis (any EP x TP mesh) it computed each
        shard's local expert slice against the GLOBAL group offsets,
        silently corrupting forward AND backward (loss off ~7e-5, grads off
        ~100% of signal, no error raised).  ``moe_dropless`` now gathers the
        expert weights over 'expert' for the compute (weight-gather EP;
        resident weights/opt state stay sharded), which restores bit-level
        SPMD parity — so the tolerances here are tight: a reappearance of
        the partitioner hole fails loudly."""
        params = mixtral.init_params(jax.random.PRNGKey(0), CFG, FP32)
        batch = _batch(jax.random.PRNGKey(1))

        def loss_fn(p, b):
            return mixtral.forward(p, b, CFG, FP32)[0]

        ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params, batch)

        mesh = build_mesh(MeshConfig(tensor_model_parallel_size=2,
                                     expert_model_parallel_size=2))
        specs = mixtral.param_specs(CFG)
        ns = functools.partial(NamedSharding, mesh)
        sh_params = jax.device_put(
            params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
        )
        sh_batch = jax.device_put(batch, ns(P(("data", "expert"))))
        with mesh, shd.use_mesh(mesh):
            loss, grads = jax.jit(jax.value_and_grad(loss_fn))(sh_params, sh_batch)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
        g = grads["layers"]["mlp"]["experts"]["down"]
        rg = ref_grads["layers"]["mlp"]["experts"]["down"]
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg), rtol=1e-3, atol=1e-5)


def test_mixtral_left_padded_matches_unpadded():
    """attention_mask: left-padded batch matches unpadded on real positions."""
    params = mixtral.init_params(jax.random.PRNGKey(0), CFG, FP32)
    ids = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 3, 128)
    ref, _ = mixtral.forward(params, {"input_ids": ids}, CFG, FP32)
    pad = 4
    padded = jnp.concatenate([jnp.zeros((1, pad), ids.dtype), ids], 1)
    mask = jnp.concatenate(
        [jnp.zeros((1, pad), jnp.int32), jnp.ones((1, 12), jnp.int32)], 1)
    out, _ = mixtral.forward(
        params, {"input_ids": padded, "attention_mask": mask}, CFG, FP32)
    np.testing.assert_allclose(
        np.asarray(out[:, pad:]), np.asarray(ref), rtol=2e-5, atol=2e-5)


class TestMoEFrequency:
    """Dense/MoE interleave (reference modeling_mixtral.py:444-451:
    layer i is MoE iff i % frequency == 0)."""

    def _cfg(self, freq):
        import dataclasses

        return mixtral.MixtralConfig(
            llama=dataclasses.replace(CFG.llama, num_layers=4),
            moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True,
                                  router_aux_loss_coef=0.02),
            moe_frequency=freq,
        )

    def test_interleaved_equals_dense_when_experts_identical(self):
        """With every expert a copy of the dense MLP weights, top-k renorm
        makes MoE(x) == MLP(x): the freq-2 model must match pure llama."""
        cfg = self._cfg(2)
        lc = cfg.llama
        lparams = llama.init_params(jax.random.PRNGKey(0), lc, FP32)
        params = mixtral.init_params(jax.random.PRNGKey(0), cfg, FP32)
        # attention/norm trees are identical by construction (same init);
        # make dense sub-layers equal llama's layers 1,3 and experts copies
        # of llama's layers 0,2 MLPs
        g, f, e = 2, 2, 4
        dense_src = jax.tree_util.tree_map(
            lambda x: x.reshape((g, f) + x.shape[1:])[:, 1:], lparams["layers"]["mlp"])
        params["layers"]["mlp"]["dense"] = dense_src
        moe_src_gate_up = np.asarray(lparams["layers"]["mlp"]["gate_up"]["w"]).reshape(
            (g, f) + lparams["layers"]["mlp"]["gate_up"]["w"].shape[1:])[:, 0]
        moe_src_down = np.asarray(lparams["layers"]["mlp"]["down"]["w"]).reshape(
            (g, f) + lparams["layers"]["mlp"]["down"]["w"].shape[1:])[:, 0]
        params["layers"]["mlp"]["moe"]["experts"]["gate_up"] = jnp.asarray(
            np.repeat(moe_src_gate_up[:, None], e, axis=1))
        params["layers"]["mlp"]["moe"]["experts"]["down"] = jnp.asarray(
            np.repeat(moe_src_down[:, None], e, axis=1))

        batch = _batch(jax.random.PRNGKey(1))
        ref_logits, _ = llama.forward(lparams, {"input_ids": batch["input_ids"]},
                                      lc, FP32)
        logits, aux = mixtral.forward(params, {"input_ids": batch["input_ids"]},
                                      cfg, FP32)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                                   rtol=2e-5, atol=2e-5)

    def test_interleaved_trains(self):
        cfg = self._cfg(2)
        params = mixtral.init_params(jax.random.PRNGKey(0), cfg, FP32)
        batch = _batch(jax.random.PRNGKey(1))

        def loss_fn(p):
            return mixtral.forward(p, batch, cfg, FP32)[0]

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        # grads reach the router, experts, AND the dense sub-layers
        assert float(np.abs(np.asarray(
            grads["layers"]["mlp"]["moe"]["router"]["w"])).max()) > 0
        assert float(np.abs(np.asarray(
            grads["layers"]["mlp"]["dense"]["gate_up"]["w"])).max()) > 0

    def test_specs_match_param_tree(self):
        cfg = self._cfg(2)
        params = mixtral.init_params(jax.random.PRNGKey(0), cfg, FP32)
        specs = mixtral.param_specs(cfg)
        flat_p = jax.tree_util.tree_structure(params)
        flat_s = jax.tree_util.tree_structure(
            specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
        assert flat_p == flat_s

    def test_indivisible_raises(self):
        import dataclasses

        cfg = dataclasses.replace(self._cfg(2),
                                  llama=dataclasses.replace(CFG.llama, num_layers=3))
        with pytest.raises(ValueError, match="frequency"):
            mixtral.init_params(jax.random.PRNGKey(0), cfg, FP32)
