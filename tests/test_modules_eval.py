"""SFT/DPO/Megatron data modules, generation, eval metrics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_training_tpu.data.modules import (
    DPODataModule,
    MegatronDataModule,
    SFTDataModule,
    load_alignment_records,
)
from neuronx_distributed_training_tpu.models import llama
from neuronx_distributed_training_tpu.models.generate import generate
from neuronx_distributed_training_tpu.tools.evaluate import (
    evaluate_sft,
    exact_match,
    rouge_l,
    score,
    token_f1,
)
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy


class CharTokenizer:
    """Deterministic toy tokenizer: one token per character."""

    eos_token_id = 1
    bos_token_id = 2

    def encode(self, s):
        return [3 + (ord(c) % 60) for c in s]


class TestSFTDataModule:
    def test_packed_batches(self):
        records = [{"input": f"q{i}", "output": "answer" * (i % 3 + 1)} for i in range(20)]
        dm = SFTDataModule(records, CharTokenizer(), seq_length=32, global_batch_size=2)
        b = next(dm.global_batches())
        assert b["input_ids"].shape == (2, 32)
        assert b["loss_mask"].shape == (2, 32)
        # prompt positions masked: at least some zeros and ones
        assert 0 < b["loss_mask"].sum() < b["loss_mask"].size

    def test_padded_mode(self):
        records = [{"input": "hi", "output": "there"}] * 8
        dm = SFTDataModule(records, CharTokenizer(), seq_length=16,
                           global_batch_size=4, packing=False)
        b = next(dm.global_batches())
        assert b["input_ids"].shape == (4, 16)

    def test_too_small_raises(self):
        with pytest.raises(ValueError, match="too small"):
            SFTDataModule([{"input": "a", "output": "b"}], CharTokenizer(),
                          seq_length=512, global_batch_size=8)


class TestDPODataModule:
    def make(self):
        records = [
            {"prompt": f"q{i}", "chosen": "good answer", "rejected": "bad"}
            for i in range(8)
        ]
        return DPODataModule(records, CharTokenizer(), seq_length=24, global_batch_size=4)

    def test_batch_keys(self):
        dm = self.make()
        b = next(dm.global_batches())
        assert set(b) >= {"chosen_input_ids", "chosen_loss_mask",
                          "rejected_input_ids", "rejected_loss_mask"}
        assert b["chosen_input_ids"].shape == (4, 24)

    def test_attach_reference_logprobs(self):
        dm = self.make()
        dm.attach_reference_logprobs({
            "reference_chosen_logps": np.zeros(8, np.float32),
            "reference_rejected_logps": np.ones(8, np.float32),
        })
        b = next(dm.global_batches())
        assert b["reference_rejected_logps"].shape == (4,)
        with pytest.raises(ValueError, match="length"):
            dm.attach_reference_logprobs({"x": np.zeros(3)})


class TestMegatronDataModule:
    def test_end_to_end(self, tmp_path):
        from neuronx_distributed_training_tpu.data.megatron import write_indexed_dataset

        rng = np.random.Generator(np.random.PCG64(0))
        docs = [rng.integers(0, 100, 50, dtype=np.int32) for _ in range(20)]
        write_indexed_dataset(tmp_path / "c", docs)
        dm = MegatronDataModule(tmp_path / "c", seq_length=16, global_batch_size=4,
                                max_steps=3)
        b = next(dm.global_batches())
        assert b["input_ids"].shape == (4, 16)
        np.testing.assert_array_equal(b["input_ids"][0][1:], b["labels"][0][:-1])


class TestGenerate:
    def test_greedy_deterministic_and_eos(self):
        cfg = llama.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=1,
            num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
            activations_checkpoint_granularity=None,
        )
        policy = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32)
        params = llama.init_params(jax.random.PRNGKey(0), cfg, policy)

        def logits_of(p, ids):
            out, _ = llama.forward(p, {"input_ids": ids}, cfg, policy)
            return out

        prompts = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
        out1 = generate(params, prompts, jnp.asarray([4]), logits_of,
                        max_new_tokens=6, eos_id=1)
        out2 = generate(params, prompts, jnp.asarray([4]), logits_of,
                        max_new_tokens=6, eos_id=1)
        assert out1.shape == (1, 10)
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
        np.testing.assert_array_equal(np.asarray(out1[:, :4]), np.asarray(prompts))

    def test_ragged_batch_right_padding_matches_solo(self):
        """A short prompt in a batch with a longer one must generate exactly
        what it generates alone — right-padding + per-row fronts means pads
        are never attended and RoPE positions are unshifted."""
        from neuronx_distributed_training_tpu.models.generate import pad_prompts

        cfg = llama.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=1,
            num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
            activations_checkpoint_granularity=None,
        )
        policy = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32)
        params = llama.init_params(jax.random.PRNGKey(1), cfg, policy)

        def logits_of(p, ids):
            out, _ = llama.forward(p, {"input_ids": ids}, cfg, policy)
            return out

        short, long = [5, 6], [9, 10, 11, 12, 13, 14]
        ids, lens = pad_prompts([short, long])
        both = generate(params, ids, lens, logits_of, max_new_tokens=4, eos_id=1)
        solo_ids, solo_lens = pad_prompts([short])
        solo = generate(params, solo_ids, solo_lens, logits_of,
                        max_new_tokens=4, eos_id=1)
        np.testing.assert_array_equal(
            np.asarray(both[0, 2:6]), np.asarray(solo[0, 2:6])
        )


class TestEvalMetrics:
    def test_rouge_l(self):
        assert rouge_l("the cat sat", "the cat sat") == 1.0
        assert rouge_l("totally different", "the cat sat") == 0.0
        assert 0 < rouge_l("the cat stood", "the cat sat") < 1.0

    def test_exact_and_f1(self):
        assert exact_match("The Cat!", "the cat") == 1.0
        assert token_f1("a b c", "a b d") == pytest.approx(2 / 3)

    def test_evaluate_sft_driver(self):
        records = [{"input": "2+2", "output": "four"}, {"input": "1+1", "output": "two"}]
        gen = lambda prompt: "four" if "2+2" in prompt else "three"
        m = evaluate_sft(records, gen)
        assert m["exact_match"] == 0.5
        assert set(m) == {"rouge_l", "f1", "exact_match"}

    def test_load_jsonl(self, tmp_path):
        f = tmp_path / "d.jsonl"
        f.write_text('{"input": "a", "output": "b"}\n{"input": "c", "output": "d"}\n')
        recs = load_alignment_records(f)
        assert len(recs) == 2 and recs[1]["output"] == "d"


class TestSamplingFilters:
    """top-k / nucleus filtering (reference evaluate.py:245-266 knobs)."""

    def test_top_k_keeps_k(self):
        from neuronx_distributed_training_tpu.models.generate import filter_logits

        logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0, 4.0]])
        out = filter_logits(logits, top_k=2)
        kept = np.isfinite(np.asarray(out)) & (np.asarray(out) > -1e30)
        np.testing.assert_array_equal(kept[0], [False, True, False, False, True])

    def test_top_p_keeps_nucleus(self):
        from neuronx_distributed_training_tpu.models.generate import filter_logits

        # softmax probs ~ [0.64, 0.24, 0.09, 0.03]; top_p=0.7 keeps first two
        logits = jnp.log(jnp.asarray([[0.64, 0.24, 0.09, 0.03]]))
        out = np.asarray(filter_logits(logits, top_p=0.7))
        kept = out > -1e30
        np.testing.assert_array_equal(kept[0], [True, True, False, False])

    def test_first_token_always_kept(self):
        from neuronx_distributed_training_tpu.models.generate import filter_logits

        logits = jnp.asarray([[10.0, 0.0, 0.0]])  # prob ~1 on token 0
        out = np.asarray(filter_logits(logits, top_p=0.1))
        assert out[0, 0] > -1e30 and (out[0, 1:] < -1e30).all()

    def test_sampled_generation_respects_top_k(self):
        from neuronx_distributed_training_tpu.models.generate import generate

        vocab = 16

        def logits_of(params, ids):
            # constant distribution strongly favoring tokens 3 and 5
            base = jnp.full((vocab,), -10.0).at[3].set(5.0).at[5].set(4.0)
            return jnp.broadcast_to(base, ids.shape + (vocab,))

        ids = jnp.zeros((2, 4), jnp.int32)
        lens = jnp.asarray([4, 4], jnp.int32)
        out = generate(None, ids, lens, logits_of, max_new_tokens=8,
                       eos_id=15, temperature=1.0, top_k=2,
                       key=jax.random.PRNGKey(0))
        gen = np.asarray(out[:, 4:])
        assert set(np.unique(gen)) <= {3, 5}
