"""MoE: routing, dropped vs dropless numerics, aux loss, EP sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.ops import moe
from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh

CFG = moe.MoEConfig(num_experts=4, top_k=2, dropless=True)
FP32 = dict(compute_dtype=jnp.float32)


def params_and_x(key, t=32, h=16, ffn=32, cfg=CFG):
    kp, kx = jax.random.split(key)
    params = moe.init_moe_params(kp, h, ffn, cfg)
    x = jax.random.normal(kx, (t, h), jnp.float32)
    return params, x


def dense_reference(params, x, cfg):
    """Every token through its top-k experts, computed naively per expert."""
    probs, idx, _ = moe.route(params["router"], x, cfg)
    t, h = x.shape
    out = np.zeros((t, h), np.float32)
    gu = np.asarray(params["experts"]["gate_up"], np.float32)
    dn = np.asarray(params["experts"]["down"], np.float32)
    xn = np.asarray(x, np.float32)
    pn, en = np.asarray(probs), np.asarray(idx)
    for ti in range(t):
        for kk in range(en.shape[1]):
            e = int(en[ti, kk])
            g_u = xn[ti] @ gu[e]
            g, u = np.split(g_u, 2)
            act = (g / (1 + np.exp(-g))) * u
            out[ti] += pn[ti, kk] * (act @ dn[e])
    return out


class TestRouting:
    def test_topk_shapes_and_norm(self):
        params, x = params_and_x(jax.random.PRNGKey(0))
        probs, idx, logits = moe.route(params["router"], x, CFG)
        assert probs.shape == (32, 2) and idx.shape == (32, 2)
        assert logits.shape == (32, 4)
        np.testing.assert_allclose(np.asarray(probs.sum(-1)), 1.0, rtol=1e-5)

    def test_sinkhorn_balances(self):
        cfg = moe.MoEConfig(num_experts=4, top_k=1, router_type="sinkhorn")
        params, x = params_and_x(jax.random.PRNGKey(1), t=256, cfg=cfg)
        _, idx, _ = moe.route(params["router"], x, cfg)
        counts = np.bincount(np.asarray(idx).ravel(), minlength=4)
        # balanced routing: no expert should starve
        assert counts.min() > 0.1 * 256 / 4, counts

    def test_aux_loss_uniform_is_one(self):
        # perfectly uniform router -> loss == 1.0 (its minimum)
        logits = jnp.zeros((64, 4))
        idx = jnp.tile(jnp.arange(4), 32).reshape(64, 2)
        loss = moe.load_balancing_loss(logits, idx, CFG)
        np.testing.assert_allclose(float(loss), 1.0, rtol=1e-5)


class TestExpertCompute:
    def test_dropless_matches_dense_reference(self):
        params, x = params_and_x(jax.random.PRNGKey(2))
        y, _ = moe.moe_dropless(params, x, CFG, compute_dtype=jnp.float32)
        ref = dense_reference(params, x, CFG)
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-4)

    def test_dropped_high_capacity_matches_dense(self):
        cfg = moe.MoEConfig(num_experts=4, top_k=2, dropless=False, capacity_factor=4.0)
        params, x = params_and_x(jax.random.PRNGKey(3), cfg=cfg)
        y, _ = moe.moe_dropped(params, x, cfg, compute_dtype=jnp.float32)
        ref = dense_reference(params, x, cfg)
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-4)

    def test_dropped_capacity_drops_tokens(self):
        cfg = moe.MoEConfig(num_experts=4, top_k=1, dropless=False, capacity_factor=0.25)
        params, x = params_and_x(jax.random.PRNGKey(4), t=64, cfg=cfg)
        y, _ = moe.moe_dropped(params, x, cfg, compute_dtype=jnp.float32)
        dropped_rows = np.all(np.asarray(y) == 0.0, axis=-1)
        assert dropped_rows.sum() > 0  # over-capacity tokens zeroed

    def test_grads_flow(self):
        params, x = params_and_x(jax.random.PRNGKey(5))

        def loss(p):
            y, _ = moe.moe_dropless(p, x, CFG, compute_dtype=jnp.float32)
            return jnp.sum(jnp.square(y))

        g = jax.grad(loss)(params)
        assert float(jnp.abs(g["experts"]["gate_up"]).sum()) > 0
        assert float(jnp.abs(g["router"]["w"]).sum()) > 0

    def test_moe_block_3d(self):
        params, _ = params_and_x(jax.random.PRNGKey(6))
        x = jax.random.normal(jax.random.PRNGKey(7), (2, 8, 16))
        y, aux = moe.moe_block(params, x, CFG, compute_dtype=jnp.float32)
        assert y.shape == (2, 8, 16)
        assert aux["router_logits"].shape == (16, 4)


class TestEP:
    def test_ep_sharded_dropped_matches(self, devices8):
        """Expert-parallel (expert axis 4) dropped-MoE matches unsharded."""
        cfg = moe.MoEConfig(num_experts=4, top_k=2, dropless=False, capacity_factor=4.0)
        params, x = params_and_x(jax.random.PRNGKey(8), cfg=cfg)
        ref, _ = moe.moe_dropped(params, x, cfg, compute_dtype=jnp.float32)

        mesh = build_mesh(MeshConfig(expert_model_parallel_size=4))
        specs = moe.moe_param_specs(cfg)
        sh_params = jax.device_put(
            params,
            jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), specs,
                is_leaf=lambda s: isinstance(s, P),
            ),
        )
        with mesh:
            y, _ = jax.jit(
                lambda p, xx: moe.moe_dropped(p, xx, cfg, compute_dtype=jnp.float32)
            )(sh_params, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-4)

    def test_ep_tp_sharded_dropless_matches(self, devices8):
        """Regression: dropless on an EP x TP mesh (STRIDED expert axis).

        XLA's SPMD partitioner has no rule for ragged_dot's group dim; with
        the expert dim sharded it silently computed local expert slices
        against global group offsets — full-signal corruption (forward off
        by the magnitude of y) with no error.  moe_dropless now gathers the
        expert weights over 'expert' for the compute; parity must be tight
        and the gradient path exact too."""
        cfg = moe.MoEConfig(num_experts=4, top_k=2, dropless=True)
        params, x = params_and_x(jax.random.PRNGKey(9), cfg=cfg)

        def fwd(p, xx):
            return moe.moe_dropless(p, xx, cfg, compute_dtype=jnp.float32)[0]

        ref = fwd(params, x)
        gref = jax.grad(lambda p, xx: (fwd(p, xx) ** 2).sum())(params, x)

        mesh = build_mesh(
            MeshConfig(tensor_model_parallel_size=2,
                       expert_model_parallel_size=2),
            devices=devices8[:4],
        )
        specs = moe.moe_param_specs(cfg)
        sh_params = jax.device_put(
            params,
            jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), specs,
                is_leaf=lambda s: isinstance(s, P),
            ),
        )
        with mesh:
            y = jax.jit(fwd)(sh_params, x)
            g = jax.jit(jax.grad(lambda p, xx: (fwd(p, xx) ** 2).sum()))(
                sh_params, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(gref),
                        jax.tree_util.tree_leaves(g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6)


class TestTokenShuffle:
    """token_shuffle_group_size (reference transformer.py:410-411): de-bias
    capacity drops from sequence position in the dropped path."""


    def test_permutation_is_bijective(self):
        from neuronx_distributed_training_tpu.ops.moe import _shuffle_permutation

        for t, g in ((64, 8), (48, 7), (5, 16), (1, 4)):
            p = np.asarray(_shuffle_permutation(t, g))
            assert sorted(p.tolist()) == list(range(t)), (t, g)

    def test_dropless_output_unchanged(self):
        """Shuffle is a dropped-path concept; dropless output is identical."""
        import dataclasses

        cfg = moe.MoEConfig(num_experts=4, top_k=2, dropless=True)
        params = moe.init_moe_params(jax.random.PRNGKey(0), 16, 32, cfg,
                                 dtype=jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16), jnp.float32)
        y0, _ = moe.moe_block(params, x, cfg, compute_dtype=jnp.float32)
        cfg2 = dataclasses.replace(cfg, token_shuffle_group_size=4)
        y1, _ = moe.moe_block(params, x, cfg2, compute_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(y0), np.asarray(y1))

    def test_dropped_shuffle_debiases_position(self):
        """With tight capacity, unshuffled drops pile onto LATE positions;
        the stride shuffle spreads them across the sequence."""
        import dataclasses

        cfg = moe.MoEConfig(num_experts=2, top_k=1, dropless=False,
                        capacity_factor=0.5)
        params = moe.init_moe_params(jax.random.PRNGKey(0), 16, 32, cfg,
                                 dtype=jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 64, 16), jnp.float32)

        def dropped_positions(c):
            y, _aux = moe.moe_block(params, x, c, compute_dtype=jnp.float32)
            # a dropped token passes through as exactly zero output
            return np.nonzero(np.all(np.asarray(y[0]) == 0.0, axis=-1))[0]

        base = dropped_positions(cfg)
        shuf = dropped_positions(
            dataclasses.replace(cfg, token_shuffle_group_size=8))
        assert len(base) > 0  # capacity 0.5 guarantees drops
        # same total drop budget (capacity unchanged)
        assert abs(len(base) - len(shuf)) <= 2
        # unshuffled: drops concentrate in the back half; shuffled: spread out
        assert np.mean(base) > 32
        assert np.mean(shuf) < np.mean(base)

    def test_shuffled_outputs_keep_token_alignment(self):
        """Kept tokens produce the same expert output with and without
        shuffle when nothing is dropped (capacity ample)."""
        import dataclasses

        cfg = moe.MoEConfig(num_experts=2, top_k=1, dropless=False,
                        capacity_factor=4.0)
        params = moe.init_moe_params(jax.random.PRNGKey(0), 16, 32, cfg,
                                 dtype=jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16), jnp.float32)
        y0, a0 = moe.moe_block(params, x, cfg, compute_dtype=jnp.float32)
        y1, a1 = moe.moe_block(
            params, x, dataclasses.replace(cfg, token_shuffle_group_size=4),
            compute_dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(y0), np.asarray(y1),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(a0["expert_idx"]),
                                      np.asarray(a1["expert_idx"]))
