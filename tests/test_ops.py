import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_training_tpu.ops import attention as attn_ops
from neuronx_distributed_training_tpu.ops import cross_entropy as ce_ops
from neuronx_distributed_training_tpu.ops import linear as linear_ops
from neuronx_distributed_training_tpu.ops import norm as norm_ops
from neuronx_distributed_training_tpu.ops import rope as rope_ops


def test_rms_norm_matches_numpy():
    params, _ = norm_ops.init_rms_norm(16)
    params["scale"] = jnp.asarray(np.random.RandomState(0).randn(16), jnp.float32)
    x = jnp.asarray(np.random.RandomState(1).randn(2, 3, 16), jnp.float32)
    out = norm_ops.apply_rms_norm(params, x, eps=1e-5)
    xn = np.asarray(x, np.float64)
    expected = xn / np.sqrt((xn**2).mean(-1, keepdims=True) + 1e-5) * np.asarray(params["scale"])
    np.testing.assert_allclose(np.asarray(out), expected, rtol=2e-5, atol=2e-5)


def test_rms_norm_upcasts_bf16():
    params, _ = norm_ops.init_rms_norm(128)
    x = jnp.ones((1, 4, 128), jnp.bfloat16) * 3.0
    out = norm_ops.apply_rms_norm(params, x)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), 1.0, rtol=1e-2)


def test_rope_rotation_properties():
    # rotating by position p then attending q.k should depend only on p_q - p_k
    d = 8
    inv = rope_ops.rope_frequencies(d, theta=10000.0)
    q = jnp.asarray(np.random.RandomState(0).randn(1, 4, 1, d), jnp.float32)
    pos = jnp.arange(4)[None, :]
    cos, sin = rope_ops.rope_cos_sin(pos, inv)
    q_rot = rope_ops.apply_rope(q, cos, sin)
    # norm preserved
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(q_rot), axis=-1),
        np.linalg.norm(np.asarray(q), axis=-1),
        rtol=1e-5,
    )
    # position 0 is identity
    np.testing.assert_allclose(np.asarray(q_rot[0, 0]), np.asarray(q[0, 0]), rtol=1e-6)


def test_rope_relative_position_invariance():
    d = 16
    inv = rope_ops.rope_frequencies(d)
    rs = np.random.RandomState(2)
    q = jnp.asarray(rs.randn(1, 1, 1, d), jnp.float32)
    k = jnp.asarray(rs.randn(1, 1, 1, d), jnp.float32)

    def score(pq, pk):
        cq, sq = rope_ops.rope_cos_sin(jnp.asarray([[pq]]), inv)
        ck, sk = rope_ops.rope_cos_sin(jnp.asarray([[pk]]), inv)
        return float(
            jnp.sum(rope_ops.apply_rope(q, cq, sq) * rope_ops.apply_rope(k, ck, sk))
        )

    assert score(5, 3) == pytest.approx(score(12, 10), rel=1e-5)


def test_core_attention_matches_numpy_softmax():
    b, s, h, d = 2, 8, 2, 4
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
    out = attn_ops.core_attention(q, k, v, causal=True)

    qn, kn, vn = (np.asarray(x, np.float64) for x in (q, k, v))
    scores = np.einsum("bqhd,bkhd->bhqk", qn, kn) / np.sqrt(d)
    mask = np.tril(np.ones((s, s), bool))
    scores = np.where(mask, scores, -np.inf)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    expected = np.einsum("bhqk,bkhd->bqhd", probs, vn)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4, atol=1e-5)


def test_gqa_repeat_kv_equivalence():
    b, s, d = 1, 6, 4
    rs = np.random.RandomState(1)
    q = jnp.asarray(rs.randn(b, s, 4, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, s, 2, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, s, 2, d), jnp.float32)
    out = attn_ops.core_attention(q, k, v)
    out_expanded = attn_ops.core_attention(q, attn_ops.repeat_kv(k, 2), attn_ops.repeat_kv(v, 2))
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_expanded), rtol=1e-6)


def test_sliding_window_mask():
    bias = attn_ops.causal_mask_bias(4, 4, sliding_window=2)
    visible = np.asarray(bias) == 0
    expected = np.array(
        [
            [1, 0, 0, 0],
            [1, 1, 0, 0],
            [0, 1, 1, 0],
            [0, 0, 1, 1],
        ],
        bool,
    )
    np.testing.assert_array_equal(visible, expected)


def test_cross_entropy_matches_scipy():
    b, s, v = 2, 4, 11
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(b, s, v), jnp.float32)
    labels = jnp.asarray(rs.randint(0, v, (b, s)))
    loss = ce_ops.cross_entropy_loss(logits, labels)
    ln = np.asarray(logits, np.float64)
    lse = np.log(np.exp(ln).sum(-1))
    ll = np.take_along_axis(ln, np.asarray(labels)[..., None], -1)[..., 0]
    np.testing.assert_allclose(float(loss), (lse - ll).mean(), rtol=1e-5)


def test_cross_entropy_ignore_index_and_mask():
    logits = jnp.zeros((1, 4, 5))
    labels = jnp.asarray([[1, 2, -100, 3]])
    loss = ce_ops.cross_entropy_loss(logits, labels)
    np.testing.assert_allclose(float(loss), np.log(5.0), rtol=1e-6)
    masked = ce_ops.cross_entropy_loss(
        logits, labels, loss_mask=jnp.asarray([[1.0, 0.0, 1.0, 1.0]])
    )
    np.testing.assert_allclose(float(masked), np.log(5.0), rtol=1e-6)


def test_logprobs_from_logits():
    logits = jnp.asarray(np.random.RandomState(0).randn(1, 3, 7), jnp.float32)
    labels = jnp.asarray([[0, 3, 6]])
    lp = ce_ops.logprobs_from_logits(logits, labels)
    ref = np.log(
        np.take_along_axis(
            np.exp(np.asarray(logits)) / np.exp(np.asarray(logits)).sum(-1, keepdims=True),
            np.asarray(labels)[..., None],
            -1,
        )[..., 0]
    )
    np.testing.assert_allclose(np.asarray(lp), ref, rtol=1e-4)


def test_vocab_padding():
    assert linear_ops.pad_vocab_size(32000, 128, 4) == 32256
    assert linear_ops.pad_vocab_size(512, 128, 4) == 512


class TestChunkedCE:
    """Fused head+CE (chunked logsumexp) vs the standard two-step path."""

    def test_loss_and_grads_match_standard(self):
        from neuronx_distributed_training_tpu.ops.cross_entropy import (
            chunked_cross_entropy_from_hidden,
            cross_entropy_loss,
        )

        key = jax.random.PRNGKey(0)
        h, v, b, s = 32, 96, 2, 10
        hidden = jax.random.normal(jax.random.fold_in(key, 1), (b, s, h))
        w = jax.random.normal(jax.random.fold_in(key, 2), (h, v)) * 0.1
        labels = jax.random.randint(jax.random.fold_in(key, 3), (b, s), 0, v)
        labels = labels.at[0, 0].set(-100)  # ignore_index coverage
        mask = jnp.ones((b, s)).at[1, :3].set(0.0)

        def standard(hidden, w):
            return cross_entropy_loss(hidden @ w, labels, loss_mask=mask)

        def chunked(hidden, w):
            return chunked_cross_entropy_from_hidden(
                hidden, w, labels, num_chunks=8, loss_mask=mask)

        ref, (gh_ref, gw_ref) = jax.value_and_grad(standard, argnums=(0, 1))(hidden, w)
        got, (gh, gw) = jax.value_and_grad(chunked, argnums=(0, 1))(hidden, w)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(gh), np.asarray(gh_ref),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref),
                                   rtol=1e-4, atol=1e-6)

    def test_indivisible_raises(self):
        from neuronx_distributed_training_tpu.ops.cross_entropy import (
            chunked_cross_entropy_from_hidden,
        )

        with pytest.raises(ValueError, match="divisible"):
            chunked_cross_entropy_from_hidden(
                jnp.zeros((1, 2, 4)), jnp.zeros((4, 10)),
                jnp.zeros((1, 2), jnp.int32), num_chunks=3)

    def test_llama_forward_knob_matches(self):
        import dataclasses

        from neuronx_distributed_training_tpu.models import llama as llama_mod
        from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

        fp32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                           softmax_dtype=jnp.float32)
        cfg = llama_mod.LlamaConfig(
            vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
            num_attention_heads=4, num_kv_heads=2, max_position_embeddings=32,
            activations_checkpoint_granularity=None,
        )
        params = llama_mod.init_params(jax.random.PRNGKey(0), cfg, fp32)
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 3, 64)
        batch = {"input_ids": ids, "labels": ids}
        ref, _ = llama_mod.forward(params, batch, cfg, fp32)
        cfg2 = dataclasses.replace(cfg, vocab_chunks=4)
        got, _ = llama_mod.forward(params, batch, cfg2, fp32)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
        # tied variant
        cfg3 = dataclasses.replace(cfg, tie_word_embeddings=True)
        params3 = llama_mod.init_params(jax.random.PRNGKey(0), cfg3, fp32)
        ref3, _ = llama_mod.forward(params3, batch, cfg3, fp32)
        got3, _ = llama_mod.forward(
            params3, batch, dataclasses.replace(cfg3, vocab_chunks=4), fp32)
        np.testing.assert_allclose(float(got3), float(ref3), rtol=1e-5)
