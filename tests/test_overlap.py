"""Engineered overlap (``optim.overlap``): knob validation with did-you-mean,
bucket-plan legality across the parallelism lattice, bucketed-vs-monolithic
bitwise parity, and the XLA_FLAGS merge contract."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.config.loader import load_config
from neuronx_distributed_training_tpu.optim.adamw import (
    AdamWConfig,
    adamw_update,
    init_opt_state,
    opt_state_specs,
)
from neuronx_distributed_training_tpu.optim.overlap import (
    BUCKET_AG_SCOPE,
    OverlapConfig,
    TPU_LHS_FLAGS,
    build_bucket_plan,
    merge_xla_flags,
    xla_lhs_flags,
)
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy


# ---------------------------------------------------------------------------
# OverlapConfig validation
# ---------------------------------------------------------------------------


class TestOverlapConfig:
    def test_defaults_all_off(self):
        ov = OverlapConfig.from_config(None)
        assert ov.zero1_bucket_mb == 0.0
        assert ov.prefetch_ag is True  # no-op while bucketing is off
        assert ov.pp_double_buffer is False
        assert ov.xla_lhs is False

    def test_unknown_key_did_you_mean(self):
        with pytest.raises(ValueError,
                           match="did you mean 'zero1_bucket_mb'"):
            OverlapConfig.from_config({"zero1_bucket_md": 32})

    def test_unknown_key_lists_valid(self):
        with pytest.raises(ValueError, match="valid: zero1_bucket_mb"):
            OverlapConfig.from_config({"bogus": 1})

    def test_non_mapping_rejected(self):
        with pytest.raises(ValueError, match="must be a mapping"):
            OverlapConfig.from_config([("zero1_bucket_mb", 32)])

    @pytest.mark.parametrize("bad", [True, "32", None])
    def test_bucket_mb_type_error(self, bad):
        with pytest.raises(ValueError, match="must be a number"):
            OverlapConfig.from_config({"zero1_bucket_mb": bad})

    def test_bucket_mb_negative(self):
        with pytest.raises(ValueError, match="must be >= 0"):
            OverlapConfig.from_config({"zero1_bucket_mb": -1})

    @pytest.mark.parametrize("knob", ["prefetch_ag", "pp_double_buffer",
                                      "xla_lhs"])
    def test_bool_knob_type_error(self, knob):
        with pytest.raises(ValueError, match=f"{knob} must be a bool"):
            OverlapConfig.from_config({knob: 1})

    def test_valid_block(self):
        ov = OverlapConfig.from_config(
            {"zero1_bucket_mb": 64, "prefetch_ag": False,
             "pp_double_buffer": True, "xla_lhs": True})
        assert ov.zero1_bucket_mb == 64.0
        assert ov.prefetch_ag is False
        assert ov.pp_double_buffer is True
        assert ov.xla_lhs is True


class TestLoaderCrossConstraints:
    """``distributed_strategy.overlap`` dies at load time with curated
    messages (the die-before-compile contract)."""

    def _base(self, ds):
        return {
            "distributed_strategy": ds,
            "data": {"global_batch_size": 8, "micro_batch_size": 1,
                     "seq_length": 64},
            "model": {"num_layers": 4, "num_attention_heads": 4},
        }

    def test_bucketing_requires_zero1(self):
        with pytest.raises(ValueError, match="requires[\\s\\S]*zero1: true"):
            load_config(self._base(
                {"zero1": False, "overlap": {"zero1_bucket_mb": 32}}))

    def test_double_buffer_requires_pp(self):
        with pytest.raises(ValueError,
                           match="pp_double_buffer requires[\\s\\S]*pipeline"):
            load_config(self._base({"overlap": {"pp_double_buffer": True}}))

    def test_unknown_key_surfaces_through_loader(self):
        with pytest.raises(ValueError, match="did you mean 'prefetch_ag'"):
            load_config(self._base({"overlap": {"prefetch_agg": True}}))

    @pytest.mark.parametrize("sched", ["1f1b", "1f1b-interleaved"])
    def test_composes_with_1f1b_schedules(self, sched):
        # bucketing + double-buffer ride both manual-VJP schedules
        cfg = load_config(self._base({
            "pipeline_model_parallel_size": 2,
            "virtual_pipeline_model_parallel_size":
                2 if sched == "1f1b-interleaved" else 1,
            "zero1": True,
            "pipeline": {"schedule": sched},
            "overlap": {"zero1_bucket_mb": 32, "pp_double_buffer": True},
        }))
        ov = OverlapConfig.from_config(
            dict(cfg["distributed_strategy"]["overlap"]))
        assert ov.zero1_bucket_mb == 32.0 and ov.pp_double_buffer


# ---------------------------------------------------------------------------
# Bucket-plan legality across the lattice
# ---------------------------------------------------------------------------


def _tiny_tree():
    """Abstract params + specs: a replicated embed, a genuinely TP-sharded
    attn weight (must fall back to the per-leaf gather), a replicated mlp,
    and a 1-D norm scale.  All dims divide 8, so every DP extent works."""
    abstract = {
        "embed": {"w": jax.ShapeDtypeStruct((32, 16), jnp.float32)},
        "layers": {
            "attn": {"w": jax.ShapeDtypeStruct((16, 16), jnp.float32)},
            "mlp": {"w": jax.ShapeDtypeStruct((16, 32), jnp.float32)},
        },
        "norm": {"scale": jax.ShapeDtypeStruct((16,), jnp.float32)},
    }
    pspecs = {
        "embed": {"w": P(None, None)},
        "layers": {"attn": {"w": P(None, "model")},
                   "mlp": {"w": P(None, None)}},
        "norm": {"scale": P(None)},
    }
    return abstract, pspecs


def _group_of(path):
    return path[0].key  # top-level tree key: embed / layers / norm


class TestBucketPlan:
    def _plan(self, mesh, *, bucket_mb, zero1=True, policy=None):
        abstract, pspecs = _tiny_tree()
        ospecs = opt_state_specs(abstract, pspecs, mesh, zero1=zero1,
                                 policy=policy or DtypePolicy())
        return build_bucket_plan(abstract, pspecs, ospecs["mu"], mesh,
                                 bucket_mb=bucket_mb, group_fn=_group_of)

    def test_dp1_mesh_returns_none(self, devices8):
        mesh = build_mesh(MeshConfig(tensor_model_parallel_size=8),
                          devices=devices8)
        assert self._plan(mesh, bucket_mb=1e-6) is None

    def test_tiny_bucket_one_per_group_reversed(self, cpu_mesh):
        plan = self._plan(cpu_mesh, bucket_mb=1e-6)
        assert [b.name for b in plan.buckets] == ["norm", "layers", "embed"]
        assert plan.dp_total == 4 and plan.dp_entry == "data"

    def test_huge_bucket_coalesces_to_one(self, cpu_mesh):
        plan = self._plan(cpu_mesh, bucket_mb=1024)
        assert len(plan.buckets) == 1
        assert plan.buckets[0].name == "norm+layers+embed"

    def test_every_leaf_exactly_once(self, cpu_mesh):
        plan = self._plan(cpu_mesh, bucket_mb=1e-6)
        idxs = [i for b in plan.buckets for i in b.idxs]
        assert sorted(idxs) == list(range(plan.num_leaves))

    def test_tp_sharded_param_falls_back_per_leaf(self, cpu_mesh):
        # attn/w is physically sharded on "model": it must ride a bucket
        # (the update is still bucketed) but NOT the combined gather
        plan = self._plan(cpu_mesh, bucket_mb=1e-6)
        abstract, _ = _tiny_tree()
        leaves = jax.tree_util.tree_flatten_with_path(abstract)[0]
        attn_pos = next(i for i, (p, _) in enumerate(leaves)
                        if "attn" in jax.tree_util.keystr(p))
        layers_bucket = next(b for b in plan.buckets if "layers" in b.name)
        assert attn_pos in layers_bucket.idxs
        assert attn_pos not in [a.pos for a in layers_bucket.ag]
        # the replicated leaves all pack
        packed = {a.pos for b in plan.buckets for a in b.ag}
        assert len(packed) == 3  # embed, mlp, norm

    def test_ep_mesh_uses_combined_dp_extent(self, devices8):
        # data=4 x expert=2: the pack extent is the full 8-way DP group
        mesh = build_mesh(MeshConfig(expert_model_parallel_size=2),
                          devices=devices8)
        plan = self._plan(mesh, bucket_mb=1e-6)
        assert plan.dp_total == 8
        assert plan.dp_entry == ("data", "expert")
        assert any(b.ag for b in plan.buckets)

    def test_zero1_off_packs_nothing(self, cpu_mesh):
        # moment specs == param specs: buckets exist (the update partition is
        # still legal) but there is no combined gather to emit
        plan = self._plan(cpu_mesh, bucket_mb=1e-6, zero1=False)
        assert all(not b.ag for b in plan.buckets)


# ---------------------------------------------------------------------------
# Bucketed-vs-monolithic parity (bitwise — same lambdas, different schedule)
# ---------------------------------------------------------------------------


def _materialize(mesh, abstract, pspecs, policy, seed):
    def build(key):
        flat, treedef = jax.tree_util.tree_flatten(abstract)
        keys = jax.random.split(key, len(flat))
        vals = [jax.random.normal(k, x.shape, jnp.float32)
                .astype(policy.param_dtype)
                for k, x in zip(keys, flat, strict=True)]
        return jax.tree_util.tree_unflatten(treedef, vals)

    ns = lambda spec: jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), spec,
        is_leaf=lambda x: isinstance(x, P))
    params = jax.jit(build, out_shardings=ns(pspecs))(
        jax.random.key(seed))
    return params, ns


@pytest.mark.parametrize("tp", [2, 4])          # dp = 8 // tp in {4, 2}
@pytest.mark.parametrize("zero1", [True, False])
@pytest.mark.parametrize("regime", ["mixed_precision", "bf16SR"])
def test_bucketed_matches_monolithic_bitwise(devices8, tp, zero1, regime):
    """The engineered path reorders collectives, not math: params, moments,
    master weights, and metrics must match the monolithic update bit for bit
    across DP extents, ZeRO-1 on/off, and the bf16-params/fp32-master
    regime."""
    mesh = build_mesh(MeshConfig(tensor_model_parallel_size=tp),
                      devices=devices8)
    policy = DtypePolicy.from_precision_config(regime)
    abstract, pspecs = _tiny_tree()
    ospecs = opt_state_specs(abstract, pspecs, mesh, zero1=zero1,
                             policy=policy)
    plan = build_bucket_plan(abstract, pspecs, ospecs["mu"], mesh,
                             bucket_mb=1e-6, group_fn=_group_of)
    assert plan is not None and len(plan.buckets) == 3

    params, ns = _materialize(mesh, abstract, pspecs, policy, seed=tp)
    grads, _ = _materialize(mesh, abstract, pspecs, DtypePolicy(), seed=99)
    cfg = AdamWConfig()

    def step(bucket_plan, params, grads, opt_state):
        return adamw_update(params, grads, opt_state, lr=1e-3, cfg=cfg,
                            policy=policy, bucket_plan=bucket_plan,
                            prefetch_ag=True)

    with mesh, shd.use_mesh(mesh):
        opt_state = jax.jit(
            functools.partial(init_opt_state, policy=policy),
            out_shardings=ns(ospecs))(params)
        mono = jax.jit(functools.partial(step, None))(
            params, grads, opt_state)
        buck_fn = jax.jit(functools.partial(step, plan))
        if zero1:
            # the combined gather actually lowers under its named scope
            hlo = buck_fn.lower(params, grads, opt_state).compile().as_text()
            assert BUCKET_AG_SCOPE in hlo
        buck = jax.jit(functools.partial(step, plan))(
            params, grads, opt_state)

    def assert_tree_equal(a, b):
        jax.tree_util.tree_map(
            lambda x, y: np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y)), a, b)

    assert_tree_equal(mono[0], buck[0])  # params
    assert_tree_equal(mono[1], buck[1])  # opt state (mu/nu/master/step)
    if regime == "bf16SR":
        assert "master" in mono[1]
    np.testing.assert_array_equal(np.asarray(mono[2]["grad_norm"]),
                                  np.asarray(buck[2]["grad_norm"]))


def test_prefetch_off_still_bitwise(cpu_mesh):
    """prefetch_ag only changes scheduling freedom (barrier chain), never
    values."""
    mesh = cpu_mesh
    policy = DtypePolicy()
    abstract, pspecs = _tiny_tree()
    ospecs = opt_state_specs(abstract, pspecs, mesh, zero1=True,
                             policy=policy)
    plan = build_bucket_plan(abstract, pspecs, ospecs["mu"], mesh,
                             bucket_mb=1e-6, group_fn=_group_of)
    params, ns = _materialize(mesh, abstract, pspecs, policy, seed=3)
    grads, _ = _materialize(mesh, abstract, pspecs, policy, seed=4)
    cfg = AdamWConfig()

    def step(prefetch, params, grads, opt_state):
        return adamw_update(params, grads, opt_state, lr=1e-3, cfg=cfg,
                            policy=policy, bucket_plan=plan,
                            prefetch_ag=prefetch)

    with mesh, shd.use_mesh(mesh):
        opt_state = jax.jit(
            functools.partial(init_opt_state, policy=policy),
            out_shardings=ns(ospecs))(params)
        on = jax.jit(functools.partial(step, True))(params, grads, opt_state)
        off = jax.jit(functools.partial(step, False))(params, grads,
                                                      opt_state)
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)),
        (on[0], on[1]), (off[0], off[1]))


# ---------------------------------------------------------------------------
# XLA_FLAGS merging
# ---------------------------------------------------------------------------


class TestMergeXlaFlags:
    def test_append_to_empty(self):
        merged, conflicts = merge_xla_flags("", ("--a=1", "--b=2"))
        assert merged == "--a=1 --b=2" and conflicts == []

    def test_user_flag_wins_and_reports(self):
        merged, conflicts = merge_xla_flags("--a=user", ("--a=ours", "--b=2"))
        assert merged == "--a=user --b=2"
        assert conflicts == [("--a", "--a=user", "--a=ours")]

    def test_identical_duplicate_silent(self):
        merged, conflicts = merge_xla_flags("--a=1", ("--a=1",))
        assert merged == "--a=1" and conflicts == []

    def test_none_base_tolerated(self):
        merged, conflicts = merge_xla_flags(None, ("--a=1",))
        assert merged == "--a=1" and conflicts == []

    def test_lhs_flags_gated_by_platform(self):
        assert xla_lhs_flags("tpu") == TPU_LHS_FLAGS
        assert xla_lhs_flags("cpu") == ()
        assert xla_lhs_flags("TPU") == TPU_LHS_FLAGS
