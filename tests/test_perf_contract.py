"""Perf contracts (analysis.perf_contract) + pipeline step timelines
(telemetry.step_timeline): the measured-runtime ratchet.

Covers the timeline reconstruction on a committed pp=2 fixture (tick
boundaries, per-stage busy/idle, measured bubble fraction, straggler
attribution), facts extraction from every accepted source, per-rule fault
injections proving each PC finding fires on a seeded regression, the
update-with-justification ratchet (refusal, byte-stability), cost-model
residual reports, the bench headline's mandatory contract-verdict field,
the CLI, and — the acceptance bar — live CPU-captured tiny-llama traces
for every manual-vjp pipeline schedule carrying measured bubble fraction +
per-stage busy/idle.  All tier-1 / CPU."""

import copy
import importlib.util
import json
from pathlib import Path

import pytest

from neuronx_distributed_training_tpu.analysis import perf_contract as pc
from neuronx_distributed_training_tpu.telemetry.step_timeline import (
    analyze_pipeline,
    pipeline_facts,
)

FIXTURE = Path(__file__).parent / "data" / "pipeline_trace_fixture.trace.json"


def _fixture_events():
    return json.loads(FIXTURE.read_text())["traceEvents"]


def _load_tool(name):
    path = Path(__file__).resolve().parents[1] / "tools" / f"{name}.py"
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# pipeline step-timeline reconstruction (committed pp=2 fixture)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fixture_pipeline():
    return analyze_pipeline(
        _fixture_events(), facts=pipeline_facts("1f1b", 2, 3, 1, 0.25))


class TestStepTimelineFixture:
    """The fixture encodes a pp=2 1f1b window [0, 800us): stage 0 computes
    ticks 0..6 and idles the drain tick 7; stage 1 idles the fill tick 0 and
    runs 80us compute + the 10us hop per tick after — so every number below
    is hand-computable."""

    def test_lanes_and_resolution(self, fixture_pipeline):
        p = fixture_pipeline
        assert p["num_lanes"] == 2
        assert p["lane_resolution"] == "device"
        assert sorted(p["stages"]) == ["/device:TPU:0", "/device:TPU:1"]
        assert p["window_seconds"] == pytest.approx(800e-6)

    def test_tick_boundaries_from_hop_markers(self, fixture_pipeline):
        # marker END times are the tick boundaries: 8 ticks per lane
        p = fixture_pipeline
        for s in p["stages"].values():
            assert s["ticks_detected"] == 8
        assert p["ticks_detected"] == 16
        assert not p["ticks_truncated"]
        rows = {(t["stage"], t["tick"]): t for t in p["ticks"]}
        assert len(rows) == 16
        assert rows[(0, 0)]["dur_us"] == pytest.approx(100.0)
        # stage 0 full through tick 6, drain-idle tick 7 (only the hop)
        assert rows[(0, 6)]["busy_fraction"] == pytest.approx(1.0)
        assert rows[(0, 7)]["busy_fraction"] == pytest.approx(0.1)
        # stage 1 fill-idle tick 0, then 90% busy (80us dot + 10us hop)
        assert rows[(1, 0)]["busy_fraction"] == pytest.approx(0.1)
        assert rows[(1, 5)]["busy_fraction"] == pytest.approx(0.9)

    def test_busy_idle_split(self, fixture_pipeline):
        s0 = fixture_pipeline["stages"]["/device:TPU:0"]
        s1 = fixture_pipeline["stages"]["/device:TPU:1"]
        assert s0["busy_seconds"] == pytest.approx(710e-6)
        assert s0["idle_seconds"] == pytest.approx(90e-6)
        assert s1["busy_seconds"] == pytest.approx(640e-6)
        assert s1["idle_seconds"] == pytest.approx(160e-6)
        # the nested all-gather adds collective time without double-counting
        # busy (it sits under a compute op)
        assert s0["collective_seconds"] == pytest.approx(110e-6)
        assert s0["compute_seconds"] == pytest.approx(630e-6)

    def test_measured_bubble_and_residual(self, fixture_pipeline):
        p = fixture_pipeline
        # idle (90 + 160) over lane-time (2 x 800)
        assert p["bubble_fraction_measured"] == pytest.approx(0.15625)
        assert p["bubble_fraction_predicted"] == pytest.approx(0.25)
        assert p["bubble_residual"] == pytest.approx(-0.09375)

    def test_straggler_attribution(self, fixture_pipeline):
        p = fixture_pipeline
        assert p["straggler_stage"] == "/device:TPU:0"
        assert p["straggler_busy_fraction"] == pytest.approx(710 / 800,
                                                             abs=1e-4)

    def test_schedule_facts_echoed(self, fixture_pipeline):
        p = fixture_pipeline
        assert (p["schedule"], p["pp"], p["num_microbatches"], p["vp"]) == \
            ("1f1b", 2, 3, 1)


class TestStepTimelineEdges:
    def test_no_pp_means_no_section(self):
        assert analyze_pipeline(
            _fixture_events(), facts=pipeline_facts("none", 1, 4)) is None
        assert analyze_pipeline(_fixture_events(), facts=None) is None

    def test_no_ops_means_no_section(self):
        assert analyze_pipeline([], facts=pipeline_facts("1f1b", 2, 4)) is None

    def test_window_fallback_without_step_annotations(self):
        # drop the StepTraceAnnotation: the span falls back to op extent
        events = [e for e in _fixture_events()
                  if "step_num" not in (e.get("args") or {})]
        p = analyze_pipeline(events, facts=pipeline_facts("1f1b", 2, 3))
        assert p is not None
        assert p["window_seconds"] == pytest.approx(800e-6)
        assert p["bubble_fraction_predicted"] is None
        assert "bubble_residual" not in p

    def test_single_lane_is_aggregate(self):
        events = [e for e in _fixture_events() if e.get("pid") != 2]
        p = analyze_pipeline(events, facts=pipeline_facts("1f1b", 2, 3))
        assert p["lane_resolution"] == "aggregate"
        assert p["num_lanes"] == 1

    def test_stage_indices_follow_numeric_device_order(self):
        # 12 lanes: lexicographic order would rank TPU:10/11 before TPU:2,
        # scrambling stage attribution on every pp >= 10 capture
        events = []
        for i in range(12):
            events.append({"ph": "M", "pid": i + 1, "name": "process_name",
                           "args": {"name": f"/device:TPU:{i}"}})
            events.append({"ph": "X", "pid": i + 1, "tid": 1,
                           "ts": i * 10, "dur": 5, "name": "fusion.1"})
            events.append({"ph": "X", "pid": i + 1, "tid": 1,
                           "ts": i * 10 + 5, "dur": 2,
                           "name": "collective-permute.1"})
        p = analyze_pipeline(events, facts=pipeline_facts("1f1b", 12, 4))
        assert p["num_lanes"] == 12
        for i in range(12):
            assert p["stages"][f"/device:TPU:{i}"]["stage"] == i

    def test_tick_rows_capped_but_counted(self):
        p = analyze_pipeline(_fixture_events(),
                             facts=pipeline_facts("1f1b", 2, 3),
                             max_tick_rows=5)
        assert len(p["ticks"]) == 5
        assert p["ticks_detected"] == 16
        assert p["ticks_truncated"]

    def test_analyze_events_embeds_section(self):
        from neuronx_distributed_training_tpu.telemetry.trace_analysis import (
            analyze_events,
        )

        s = analyze_events(_fixture_events(),
                           pipeline=pipeline_facts("1f1b", 2, 3, 1, 0.25))
        assert s["pipeline"]["bubble_fraction_measured"] == pytest.approx(
            0.15625)
        # without facts the summary shape is unchanged
        assert "pipeline" not in analyze_events(_fixture_events())


# ---------------------------------------------------------------------------
# facts extraction
# ---------------------------------------------------------------------------


def _bench_line(**over):
    line = {
        "metric": "llama3_8B_pretrain_mfu", "value": 66.59,
        "unit": "percent_mfu", "vs_baseline": 1.48,
        "regime": "mixed_precision", "device": "TPU v5 lite",
        "seq_len": 8192, "num_layers": 9, "pipeline_schedule": "none",
        "ms_per_step": 905.0, "tokens_per_sec_per_chip": 28950.0,
        "mfu": 0.6659, "achieved_overlap": 0.62,
        "exposed_collective_seconds": 0.031,
        "overlap_by_class": {"all-gather": 0.55, "reduce-scatter": 0.71},
        "bubble_fraction_predicted": 0.0,
    }
    line.update(over)
    return line


def _facts(**over):
    """Canonical facts with a full measured surface (the differ's input)."""
    f = pc.perf_facts_from_bench(_bench_line())
    f["overlap_by_class"] = {
        "all-gather": {"achieved_overlap": 0.55, "exposed_seconds": 0.8,
                       "wire_seconds": 1.8},
        "reduce-scatter": {"achieved_overlap": 0.71, "exposed_seconds": 0.2,
                           "wire_seconds": 0.7},
    }
    f["bubble_fraction_measured"] = 0.10
    f["bubble_fraction_predicted"] = 0.12
    f["residuals"] = {"total": {"ratio": 1.10}}
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(f.get(k), dict):
            f[k] = copy.deepcopy(f[k])
            f[k].update(v)
        else:
            f[k] = v
    return f


class TestFactsExtraction:
    def test_from_bench_line(self):
        f = pc.perf_facts_from_bench(_bench_line())
        assert f["version"] == pc.FACTS_VERSION
        assert f["step_time_ms"] == pytest.approx(905.0)
        assert f["mfu"] == pytest.approx(0.6659)
        assert f["workload"]["device"] == "TPU v5 lite"
        assert f["overlap_by_class"]["all-gather"]["achieved_overlap"] == \
            pytest.approx(0.55)

    def test_zero_bubble_fraction_survives_extraction(self):
        # a fully-busy aggregate lane rounds to exactly 0.0 — falsy, but a
        # MEASUREMENT; it must not fall through to None (which would
        # silently disable the PC301 bubble ratchet for the topology)
        f = pc.perf_facts_from_bench(_bench_line(bubble_fraction_measured=0.0))
        assert f["bubble_fraction_measured"] == 0.0

    def test_mfu_falls_back_to_percent_value(self):
        line = _bench_line()
        del line["mfu"]
        f = pc.perf_facts_from_bench(line)
        assert f["mfu"] == pytest.approx(0.6659)

    def test_from_trace_summary(self):
        summary = {
            "achieved_overlap": 0.4, "exposed_collective_seconds": 0.02,
            "top_ops": [],
            "overlap_by_class": {"all-reduce": {
                "achieved_overlap": 0.4, "exposed_seconds": 0.02,
                "wire_seconds": 0.033}},
            "pipeline": {"schedule": "1f1b",
                         "bubble_fraction_measured": 0.21,
                         "bubble_fraction_predicted": 0.25},
        }
        f = pc.perf_facts_from_trace_summary(summary)
        assert f["bubble_fraction_measured"] == pytest.approx(0.21)
        assert f["step_time_ms"] is None
        assert f["workload"]["schedule"] == "1f1b"

    def test_from_run_dir(self, tmp_path):
        (tmp_path / "run_summary.json").write_text(json.dumps({
            "model_family": "LlamaConfig", "n_chips": 8, "seq_len": 32,
            "global_batch_size": 8, "pipeline_schedule": "1f1b",
            "bubble_fraction_predicted": 0.3333,
        }))
        (tmp_path / "trace_summary.json").write_text(json.dumps({
            "achieved_overlap": 0.5, "exposed_collective_seconds": 0.01,
            "overlap_by_class": {},
            "pipeline": {"bubble_fraction_measured": 0.08,
                         "schedule": "1f1b"},
        }))
        (tmp_path / "metrics.jsonl").write_text(
            json.dumps({"step": 3, "mfu": 0.02,
                        "tokens_per_sec_per_chip": 1000.0}) + "\n"
            + "{torn line")
        f = pc.perf_facts_from_run(tmp_path)
        assert f["mfu"] == pytest.approx(0.02)
        assert f["bubble_fraction_measured"] == pytest.approx(0.08)
        assert f["bubble_fraction_predicted"] == pytest.approx(0.3333)
        # step time derives from the SAME throughput window MFU uses:
        # gbs * seq / (tokens_per_sec_per_chip * chips)
        assert f["step_time_ms"] == pytest.approx(8 * 32 / 8000 * 1e3)

    def test_load_facts_dispatch(self, tmp_path):
        bench = tmp_path / "bench.json"
        bench.write_text(json.dumps(_bench_line()))
        assert pc.load_facts(bench)["step_time_ms"] == pytest.approx(905.0)
        # stdout capture: the JSON line is the LAST parseable line
        noisy = tmp_path / "capture.txt"
        noisy.write_text("bench: warmup done\n"
                         + json.dumps(_bench_line(ms_per_step=1.0)) + "\n")
        assert pc.load_facts(noisy)["step_time_ms"] == pytest.approx(1.0)
        # jsonl evidence log: last line wins
        log = tmp_path / "measured.jsonl"
        log.write_text(json.dumps(_bench_line(ms_per_step=2.0)) + "\n"
                       + json.dumps(_bench_line(ms_per_step=3.0)) + "\n")
        assert pc.load_facts(log)["step_time_ms"] == pytest.approx(3.0)
        # canonical facts pass through
        assert pc.load_facts(_facts())["version"] == pc.FACTS_VERSION
        with pytest.raises(pc.PerfContractError):
            pc.load_facts(tmp_path / "missing.json")
        with pytest.raises(pc.PerfContractError):
            pc.load_facts({"unrecognized": True})

    def test_default_key(self):
        assert pc.default_key(_facts()) == "tpu_v5_lite_bench"
        f = pc.perf_facts_from_bench(_bench_line(device="cpu"))
        assert pc.default_key(f) == "cpu_bench"


# ---------------------------------------------------------------------------
# the differ: every PC rule fires on a seeded regression
# ---------------------------------------------------------------------------


def _rules(report):
    return {f.rule for f in report.findings}


class TestDiffRules:
    def test_in_band_drift_is_clean(self):
        old = _facts()
        new = _facts(step_time_ms=old["step_time_ms"] * 1.05)
        rep = pc.diff_facts(old, new)
        assert not rep.findings, rep.format()

    def test_pc101_step_time_growth(self):
        rep = pc.diff_facts(_facts(), _facts(step_time_ms=905.0 * 1.5))
        assert _rules(rep) == {"PC101"}
        assert rep.failed("error")
        f = rep.findings[0]
        assert "905" in f.message and "25%" in f.message

    def test_pc102_mfu_fall(self):
        rep = pc.diff_facts(_facts(), _facts(mfu=0.55))
        assert _rules(rep) == {"PC102"}

    def test_pc102_throughput_without_mfu(self):
        old, new = _facts(mfu=None), _facts(mfu=None,
                                            tokens_per_sec=28950.0 * 0.5)
        rep = pc.diff_facts(old, new)
        assert _rules(rep) == {"PC102"}
        assert "tokens/sec" in rep.findings[0].message

    def test_pc110_improvement_is_info(self):
        rep = pc.diff_facts(_facts(), _facts(step_time_ms=905.0 * 0.5))
        assert _rules(rep) == {"PC110"}
        assert not rep.failed("error")

    def test_pc201_per_class_overlap_fall_names_class(self):
        new = _facts(overlap_by_class={
            "all-gather": {"achieved_overlap": 0.20, "exposed_seconds": 0.8,
                           "wire_seconds": 1.8}})
        rep = pc.diff_facts(_facts(), new)
        assert _rules(rep) == {"PC201"}
        f = rep.findings[0]
        assert "all-gather" in f.message and "ZeRO-1" in f.message
        assert f.location == "all-gather"

    def test_pc202_exposed_growth_names_class_and_axes(self):
        new = _facts(overlap_by_class={
            "all-gather": {"achieved_overlap": 0.55, "exposed_seconds": 2.1,
                           "wire_seconds": 3.1}})
        rep = pc.diff_facts(_facts(), new)
        assert _rules(rep) == {"PC202"}
        msg = rep.findings[0].message
        assert "exposed all-gather seconds grew" in msg
        assert "0.8s -> 2.1s" in msg and "[dp/tp]" in msg

    def test_pc202_total_exposed_growth(self):
        old = _facts(overlap_by_class={})
        new = _facts(overlap_by_class={},
                     exposed_collective_seconds=0.031 * 3)
        rep = pc.diff_facts(old, new)
        assert _rules(rep) == {"PC202"}
        assert rep.findings[0].location == "overall"

    def test_pc301_measured_bubble_growth(self):
        rep = pc.diff_facts(_facts(), _facts(bubble_fraction_measured=0.30,
                                             bubble_fraction_predicted=0.32))
        assert _rules(rep) == {"PC301"}
        assert "bubble" in rep.findings[0].message

    def test_pc302_measured_beyond_predicted_band(self):
        # baseline-independent: fires even when the baseline agrees
        old = _facts(bubble_fraction_measured=0.30,
                     bubble_fraction_predicted=0.12)
        new = _facts(bubble_fraction_measured=0.30,
                     bubble_fraction_predicted=0.12)
        rep = pc.diff_facts(old, new)
        assert _rules(rep) == {"PC302"}
        assert "calibration band" in rep.findings[0].message

    def test_pc401_residual_drift(self):
        rep = pc.diff_facts(
            _facts(), _facts(residuals={"total": {"ratio": 1.60}}))
        assert _rules(rep) == {"PC401"}
        assert "decalibrated" in rep.findings[0].message

    def test_pc001_workload_identity_mismatch_short_circuits(self):
        new = _facts(step_time_ms=9999.0)
        new["workload"] = dict(new["workload"], seq_len=4096)
        rep = pc.diff_facts(_facts(), new)
        assert _rules(rep) == {"PC001"}  # nothing else compared
        assert "seq_len" in rep.findings[0].message

    def test_pc001_version_mismatch(self):
        old = _facts()
        old["version"] = 0
        rep = pc.diff_facts(old, _facts())
        assert _rules(rep) == {"PC001"}

    def test_custom_noise_bands_respected(self):
        rep = pc.diff_facts(_facts(), _facts(step_time_ms=905.0 * 1.5),
                            noise={"step_time_frac": 1.0})
        assert not rep.findings


# ---------------------------------------------------------------------------
# the ratchet: baselines, refusal, byte-stability
# ---------------------------------------------------------------------------


class TestRatchet:
    def test_no_baseline_is_pc000(self, tmp_path):
        rep = pc.check_perf("v9z_bench", _facts(), baselines_dir=tmp_path)
        assert _rules(rep) == {"PC000"}
        assert rep.stats["no_baseline"] is True

    def test_update_then_check_round_trip(self, tmp_path):
        path, rep = pc.update_baseline("k", _facts(), baselines_dir=tmp_path)
        assert path.exists() and not rep.findings
        rep = pc.check_perf("k", _facts(), baselines_dir=tmp_path)
        assert not rep.findings
        snap = json.loads(path.read_text())
        assert snap["justifications"] == ["initial perf baseline"]
        assert snap["noise"]["step_time_frac"] == pytest.approx(
            pc.DEFAULT_NOISE["step_time_frac"])

    def test_rewrite_is_byte_stable(self, tmp_path):
        path, _ = pc.update_baseline("k", _facts(), baselines_dir=tmp_path)
        first = path.read_bytes()
        path2, _ = pc.update_baseline("k", _facts(), baselines_dir=tmp_path)
        assert path2 == path and path.read_bytes() == first

    def test_regression_refuses_without_justify(self, tmp_path):
        path, _ = pc.update_baseline("k", _facts(), baselines_dir=tmp_path)
        before = path.read_bytes()
        with pytest.raises(pc.PerfContractError, match="PC101"):
            pc.update_baseline("k", _facts(step_time_ms=905.0 * 2),
                               baselines_dir=tmp_path)
        # a refused update must leave the committed file untouched
        assert path.read_bytes() == before

    def test_justified_regression_recorded_in_file(self, tmp_path):
        pc.update_baseline("k", _facts(), baselines_dir=tmp_path)
        path, rep = pc.update_baseline(
            "k", _facts(step_time_ms=905.0 * 2),
            justify="remat default flipped: +2x step for -40% HBM",
            baselines_dir=tmp_path)
        snap = json.loads(path.read_text())
        assert snap["justifications"][-1].startswith("remat default flipped")
        assert snap["facts"]["step_time_ms"] == pytest.approx(1810.0)

    def test_improvement_commits_silently(self, tmp_path):
        pc.update_baseline("k", _facts(), baselines_dir=tmp_path)
        path, rep = pc.update_baseline(
            "k", _facts(step_time_ms=905.0 * 0.5), baselines_dir=tmp_path)
        snap = json.loads(path.read_text())
        assert snap["justifications"] == ["initial perf baseline"]
        assert snap["facts"]["step_time_ms"] == pytest.approx(452.5)
        assert {f.rule for f in rep.findings} == {"PC110"}

    def test_baseline_noise_bands_drive_the_check(self, tmp_path):
        pc.update_baseline("k", _facts(), baselines_dir=tmp_path,
                           noise={"step_time_frac": 3.0})
        rep = pc.check_perf("k", _facts(step_time_ms=905.0 * 4.5),
                            baselines_dir=tmp_path)
        assert _rules(rep) == {"PC101"}
        rep = pc.check_perf("k", _facts(step_time_ms=905.0 * 3.5),
                            baselines_dir=tmp_path)
        assert not rep.findings

    def test_bench_verdict_shapes(self, tmp_path):
        v = pc.bench_verdict("k", _facts(), baselines_dir=tmp_path)
        assert v == {"key": "k", "verdict": "no_baseline",
                     "no_baseline": True}
        pc.update_baseline("k", _facts(), baselines_dir=tmp_path)
        assert pc.bench_verdict("k", _facts(),
                                baselines_dir=tmp_path)["verdict"] == "clean"
        v = pc.bench_verdict("k", _facts(step_time_ms=905.0 * 2),
                             baselines_dir=tmp_path)
        assert v["verdict"] == "error"
        assert v["findings"][0]["rule"] == "PC101"

    def test_committed_cpu_baseline_exists_and_loads(self):
        # the verify-gate baseline shipped with the repo
        snap = pc.load_baseline("cpu_bench")
        assert snap is not None
        assert snap["facts"]["workload"]["device"] == "cpu"
        assert snap["noise"]["step_time_frac"] >= 1.0  # CPU wall clocks vary


# ---------------------------------------------------------------------------
# cost-model residuals
# ---------------------------------------------------------------------------


class TestResiduals:
    EST = {"step_seconds": 0.10, "compute_seconds": 0.07,
           "comms_seconds": 0.02, "bubble_seconds": 0.01}

    def test_total_only(self):
        r = pc.residual_report(self.EST, {"step_seconds": 0.15})
        assert r["total"]["ratio"] == pytest.approx(1.5)
        assert r["comms"]["measured_exposed_seconds"] is None
        assert r["comms"]["ratio"] is None
        assert r["bubble"]["measured_fraction"] is None
        assert r["compute"]["measured_seconds"] is None

    def test_full_surface(self):
        r = pc.residual_report(self.EST, {
            "step_seconds": 0.12, "exposed_collective_seconds": 0.03,
            "bubble_fraction_measured": 0.25})
        assert r["total"]["ratio"] == pytest.approx(1.2)
        assert r["comms"]["ratio"] == pytest.approx(1.5)
        assert r["bubble"]["predicted_fraction"] == pytest.approx(0.1)
        assert r["bubble"]["residual"] == pytest.approx(0.15)
        # measured compute = step - exposed - bubble*step
        assert r["compute"]["measured_seconds"] == pytest.approx(
            0.12 - 0.03 - 0.25 * 0.12)

    def test_never_negative_compute(self):
        r = pc.residual_report(self.EST, {
            "step_seconds": 0.01, "exposed_collective_seconds": 0.05,
            "bubble_fraction_measured": 0.5})
        assert r["compute"]["measured_seconds"] == 0.0


# ---------------------------------------------------------------------------
# bench.py: the mandatory contract-verdict field + provenance
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def bench_mod():
    path = Path(__file__).resolve().parents[1] / "bench.py"
    spec = importlib.util.spec_from_file_location("bench_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestBenchContract:
    def test_emit_refuses_headline_without_verdict(self, bench_mod):
        with pytest.raises(RuntimeError, match="perf_contract"):
            bench_mod.emit({"metric": "llama3_8B_pretrain_mfu", "value": 1.0})

    def test_emit_accepts_headline_with_verdict(self, bench_mod, capsys):
        bench_mod.emit({"metric": "llama3_8B_pretrain_mfu", "value": 1.0,
                        "perf_contract": {"verdict": "no_baseline"}})
        line = json.loads(capsys.readouterr().out.strip())
        assert line["perf_contract"]["verdict"] == "no_baseline"

    def test_non_headline_lines_unaffected(self, bench_mod, capsys):
        bench_mod.emit({"note": "not a metric line"})
        assert json.loads(capsys.readouterr().out.strip())["note"]

    def test_fail_json_carries_provenance_and_verdict(self, bench_mod,
                                                      capsys):
        bench_mod.fail_json("no backend", provenance={
            "acquire_mode": "direct", "connect_phase": "plugin-init"})
        line = json.loads(capsys.readouterr().out.strip())
        assert line["perf_contract"] == {"verdict": "no_measurement"}
        assert line["provenance"]["connect_phase"] == "plugin-init"
        assert line["value"] == 0.0


# ---------------------------------------------------------------------------
# tools/perf_contract.py CLI
# ---------------------------------------------------------------------------


class TestPerfContractCLI:
    def _run(self, tool, argv):
        with pytest.raises(SystemExit) as exc:
            tool.main(argv)
        return exc.value.code

    def test_check_no_baseline_fails_then_allow_missing(self, tmp_path,
                                                        capsys):
        tool = _load_tool("perf_contract")
        src = tmp_path / "bench.json"
        src.write_text(json.dumps(_bench_line()))
        rc = self._run(tool, ["--check", str(src),
                              "--baselines-dir", str(tmp_path / "b")])
        assert rc == 1
        assert "no_baseline" in capsys.readouterr().out
        rc = self._run(tool, ["--check", str(src), "--allow-missing",
                              "--baselines-dir", str(tmp_path / "b")])
        assert rc == 0

    def test_update_check_regress_cycle_with_json(self, tmp_path, capsys):
        tool = _load_tool("perf_contract")
        bdir = str(tmp_path / "b")
        good = tmp_path / "good.json"
        good.write_text(json.dumps(_bench_line()))
        assert self._run(tool, ["--update-baselines", str(good),
                                "--baselines-dir", bdir]) == 0
        assert self._run(tool, ["--check", str(good),
                                "--baselines-dir", bdir]) == 0
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(_bench_line(ms_per_step=905.0 * 2)))
        capsys.readouterr()
        rc = self._run(tool, ["--check", str(bad), "--baselines-dir", bdir,
                              "--json", "-"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "PC101" in out
        payload = json.loads(out.strip().splitlines()[-1])  # last-line JSON
        assert payload["reports"][0]["verdict"] == "error"
        # the refused update leaves no trace either
        assert self._run(tool, ["--update-baselines", str(bad),
                                "--baselines-dir", bdir]) == 1
        assert self._run(tool, ["--update-baselines", str(bad),
                                "--baselines-dir", bdir,
                                "--justify", "deliberate"]) == 0

    def test_unknown_noise_band_rejected(self, tmp_path, capsys):
        tool = _load_tool("perf_contract")
        src = tmp_path / "bench.json"
        src.write_text(json.dumps(_bench_line()))
        rc = self._run(tool, ["--check", str(src), "--noise", "bogus=1"])
        assert rc == 2  # argparse error


# ---------------------------------------------------------------------------
# report surfaces
# ---------------------------------------------------------------------------


class TestReportSurfaces:
    def test_trace_report_renders_pipeline_section(self, tmp_path, capsys):
        from neuronx_distributed_training_tpu.telemetry.trace_analysis import (
            analyze_events,
        )

        tr = _load_tool("trace_report")
        summary = analyze_events(_fixture_events(),
                                 pipeline=pipeline_facts("1f1b", 2, 3, 1,
                                                         0.25))
        p = tmp_path / "trace_summary.json"
        p.write_text(json.dumps(summary))
        assert tr.main([str(p)]) == 0
        out = capsys.readouterr().out
        assert "pipeline timeline" in out
        assert "bubble_fraction_measured" in out
        assert "straggler_stage" in out
        assert "/device:TPU:0" in out and "/device:TPU:1" in out
        assert "tick gantt" in out

    def test_trace_report_gantt_aligns_unequal_tick_counts(self, capsys):
        """Compacted timelines: stages detect different tick counts, so the
        Gantt columns are TIME buckets — a stage with fewer ticks must not
        be stretched to the full axis (the old per-tick-index rendering
        assumed a shared tick axis)."""
        tr = _load_tool("trace_report")
        summary = {"pipeline": {
            "schedule": "1f1b", "pp": 2, "num_microbatches": 4, "vp": 1,
            "lane_resolution": "device", "num_lanes": 2,
            "bubble_fraction_measured": 0.2,
            "stages": {"/device:TPU:0": {"stage": 0, "ticks_detected": 4,
                                         "busy_seconds": 1.0},
                       "/device:TPU:1": {"stage": 1, "ticks_detected": 2,
                                         "busy_seconds": 1.0}},
            "straggler_stage": "/device:TPU:0",
            "ticks": (
                # stage 0: four 100us ticks covering [0, 400us)
                [{"stage": 0, "tick": t, "start_us": t * 100.0,
                  "dur_us": 100.0, "busy_fraction": 1.0} for t in range(4)]
                # stage 1: TWO ticks, busy only in the middle [100, 300us)
                + [{"stage": 1, "tick": 0, "start_us": 100.0,
                    "dur_us": 100.0, "busy_fraction": 1.0},
                   {"stage": 1, "tick": 1, "start_us": 200.0,
                    "dur_us": 100.0, "busy_fraction": 1.0}]),
        }}
        out = tr.render(summary)
        bars = {}
        for line in out.splitlines():
            if "|" in line and "stage" in line:
                stage = int(line.split("|")[0].split()[-1])
                bars[stage] = line.split("|")[1]
        # shared time axis: equal bar widths, 4 buckets
        assert len(bars[0]) == len(bars[1]) == 4
        assert bars[0] == "####"
        # stage 1's ticks cover only [100, 300): idle columns at both ends
        assert bars[1] == " ## "

    def test_metrics_report_renders_provenance_and_verdict(self, tmp_path,
                                                           capsys):
        mr = _load_tool("metrics_report")
        line = dict(_bench_line(),
                    provenance={"acquire_mode": "direct",
                                "connect_phase": "connected",
                                "plugin_init_seconds": 1.2,
                                "device_kind": "TPU v5 lite"},
                    perf_contract={"verdict": "error", "key": "cpu_bench",
                                   "findings": [{"rule": "PC101",
                                                 "message": "step time grew"}]},
                    bubble_fraction_measured=0.11)
        p = tmp_path / "BENCH_test.json"
        p.write_text(json.dumps(line))
        assert mr.main([str(p)]) == 0
        out = capsys.readouterr().out
        assert "bench provenance" in out
        assert "connect_phase" in out and "connected" in out
        assert "perf contract" in out and "PC101" in out
        assert "bubble_fraction_measured" in out

    def test_planner_calibration_audit_trail(self, tmp_path):
        from neuronx_distributed_training_tpu.autotune import plan_config
        from neuronx_distributed_training_tpu.telemetry.trace_analysis import (
            analyze_events,
        )

        summary = analyze_events(_fixture_events(),
                                 pipeline=pipeline_facts("1f1b", 2, 3, 1,
                                                         0.25))
        p = tmp_path / "trace_summary.json"
        p.write_text(json.dumps(summary))
        cfg = {
            "name": "t", "model_source": "hf",
            "trainer": {"max_steps": 1},
            "distributed_strategy": {"tensor_model_parallel_size": 2},
            "data": {"seq_length": 64, "global_batch_size": 8,
                     "micro_batch_size": 1, "synthetic": True},
            "model": {"architecture": "llama", "vocab_size": 256,
                      "hidden_size": 64, "intermediate_size": 128,
                      "num_layers": 4, "num_attention_heads": 4,
                      "num_key_value_heads": 2,
                      "max_position_embeddings": 64},
            "precision": {"type": "mixed_precision"},
        }
        rep = plan_config(cfg, chips=8, topology="v5e", audit=False,
                          top_k=3, calibration=str(p))
        assert rep.error is None
        cf = rep.calibration_facts
        assert cf is not None
        assert cf["bubble_fraction_measured"] == pytest.approx(0.15625)
        assert "calibration audit" in rep.format()
        assert "calibration_facts" in rep.to_dict()
        # pp plans exist on 8 chips: when the winner is pipelined the audit
        # records its predicted fraction + the residual
        if cf.get("winner_bubble_residual") is not None:
            assert cf["winner_bubble_fraction_predicted"] is not None


# ---------------------------------------------------------------------------
# acceptance: live CPU-captured tiny-llama traces, every manual-vjp schedule
# ---------------------------------------------------------------------------


def _pp_cfg(tmp_path, schedule, vp=1, num_layers=2):
    return {
        "name": f"pt_{schedule.replace('-', '_')}", "model_source": "hf",
        "seed": 7,
        "trainer": {"max_steps": 4, "log_every_n_steps": 1},
        "exp_manager": {"exp_dir": str(tmp_path / "exp"),
                        "create_tensorboard_logger": False,
                        "log_files": False,
                        "telemetry": {"trace": {"enabled": True,
                                                "start_step": 1,
                                                "num_steps": 2}}},
        "distributed_strategy": {
            "pipeline_model_parallel_size": 2,
            **({"virtual_pipeline_model_parallel_size": vp} if vp > 1
               else {}),
            "pipeline": {"schedule": schedule},
        },
        "data": {"global_batch_size": 8, "micro_batch_size": 1,
                 "seq_length": 32, "synthetic": True},
        "model": {"vocab_size": 128, "hidden_size": 64,
                  "intermediate_size": 128, "num_layers": num_layers,
                  "num_attention_heads": 4, "num_key_value_heads": 2,
                  "max_position_embeddings": 32,
                  "optim": {"name": "adamw_fp32OptState", "lr": 1e-3}},
        "precision": {"type": "mixed_precision"},
    }


@pytest.mark.parametrize("schedule,vp,layers", [
    ("1f1b", 1, 2),
    ("1f1b-zb", 1, 2),
    ("1f1b-interleaved", 2, 4),
])
def test_live_manual_vjp_schedule_trace_carries_measured_bubble(
        tmp_path, devices8, schedule, vp, layers):
    """The acceptance bar: a CPU-captured tiny-llama trace for EVERY
    manual-vjp schedule must land measured bubble fraction + per-stage
    busy/idle in trace_summary.json, and run_summary.json must carry
    bubble_fraction_measured beside bubble_fraction_predicted."""
    import numpy as np

    from neuronx_distributed_training_tpu.config.loader import load_config
    from neuronx_distributed_training_tpu.trainer.loop import Trainer

    cfg = load_config(_pp_cfg(tmp_path, schedule, vp=vp, num_layers=layers))
    t = Trainer.from_config(cfg, enable_checkpointing=False)
    assert t.pipeline_schedule == schedule
    metrics = t.fit()
    assert np.isfinite(metrics["loss"])
    run = (tmp_path / "exp" / cfg["name"] / "version_0")
    summary = json.loads((run / "trace_summary.json").read_text())
    pipe = summary.get("pipeline")
    assert pipe is not None, "traced pp run must carry the pipeline section"
    assert pipe["schedule"] == schedule and pipe["pp"] == 2
    mb = pipe["bubble_fraction_measured"]
    assert mb is not None and 0.0 <= mb <= 1.0
    assert pipe["stages"], "per-stage busy/idle table missing"
    for s in pipe["stages"].values():
        assert s["busy_seconds"] > 0
        assert s["idle_seconds"] >= 0
        assert s["ticks_detected"] > 0
    assert pipe["straggler_stage"] in pipe["stages"]
    # predicted fraction rides along so the residual is self-contained
    assert pipe["bubble_fraction_predicted"] == pytest.approx(
        json.loads((run / "run_summary.json").read_text())
        ["bubble_fraction_predicted"], abs=1e-6)
    run_summary = json.loads((run / "run_summary.json").read_text())
    assert run_summary["bubble_fraction_measured"] == pytest.approx(mb)
    assert run_summary["trace"]["pipeline"]["schedule"] == schedule
    # and the perf-contract facts extractor reads the run dir whole
    facts = pc.perf_facts_from_run(run)
    assert facts["bubble_fraction_measured"] == pytest.approx(mb)


# ---------------------------------------------------------------------------
# compacted executions: committed pp=2 fixture where tick count != lockstep T
# ---------------------------------------------------------------------------


COMPACTED_FIXTURE = Path(__file__).parent / "data" \
    / "pipeline_trace_compacted_fixture.trace.json"


class TestCompactedTimelineFixture:
    """The work-compacted executor's timeline: the committed fixture encodes
    a pp=2 1f1b nm=4 COMPACTED window [0, 600us) — span 6 ticks where the
    lockstep trip count was 7.  Stage 0 runs F full ticks 0..4 and a 40us
    drain tail; stage 1 fill-idles tick 0 (only the gated hop runs) and
    drain-idles tick 5.  Every number is hand-computable, and the fill/drain
    idle is now VISIBLE idle (the lockstep executor burned compute there —
    the 'no phantom masked-tick compute' property)."""

    @pytest.fixture(scope="class")
    def compacted(self):
        from neuronx_distributed_training_tpu.parallel.pipeline import (
            predicted_bubble_fraction,
            work_table,
        )

        events = json.loads(COMPACTED_FIXTURE.read_text())["traceEvents"]
        return analyze_pipeline(events, facts=pipeline_facts(
            "1f1b", 2, 4, 1, predicted_bubble_fraction("1f1b", 2, 4, 1),
            ticks_per_step=work_table("1f1b", 2, 4, 1).tick_counts()))

    def test_tick_count_is_compacted_not_lockstep(self, compacted):
        p = compacted
        # 6 compacted ticks per lane resolved from the pp-hop markers —
        # NOT the lockstep T = nm + 2pp - 1 = 7
        lockstep = p["ticks_per_step"]["lockstep_span"]
        assert lockstep == 7
        for s in p["stages"].values():
            assert s["ticks_detected"] == 6
        assert p["ticks_detected"] == 12
        assert p["ticks_per_step"]["span"] == 6
        assert p["ticks_per_step"]["f_ticks"] == 5
        assert p["ticks_per_step"]["b_ticks"] == 5

    def test_busy_idle_split(self, compacted):
        s0 = compacted["stages"]["/device:TPU:0"]
        s1 = compacted["stages"]["/device:TPU:1"]
        # stage 0: 5 full ticks + (40us tail + 10us hop) in the drain tick
        assert s0["busy_seconds"] == pytest.approx(550e-6)
        assert s0["idle_seconds"] == pytest.approx(50e-6)
        # stage 1: fill tick 0 and drain tick 5 are 10us hop + 90us IDLE —
        # real idle, not burned masked compute
        assert s1["busy_seconds"] == pytest.approx(420e-6)
        assert s1["idle_seconds"] == pytest.approx(180e-6)

    def test_measured_bubble_lands_in_band(self, compacted):
        p = compacted
        # idle (50 + 180) over lane-time (2 x 600)
        assert p["bubble_fraction_measured"] == pytest.approx(230 / 1200,
                                                              abs=1e-6)
        # the compacted prediction is the table's own accounting: 0.2 for
        # 1f1b pp=2 nm=4 — the measurement lands within the PC302 band
        assert p["bubble_fraction_predicted"] == pytest.approx(0.2)
        assert abs(p["bubble_residual"]) < pc.DEFAULT_NOISE["bubble_abs"]

    def test_no_pc302_on_compacted_run(self, compacted):
        from neuronx_distributed_training_tpu.analysis.report import (
            AuditReport,
        )

        facts = pc.perf_facts_from_trace_summary({"pipeline": compacted})
        rep = AuditReport(config="t")
        pc.calibration_findings(facts, pc.DEFAULT_NOISE, rep)
        assert not [f for f in rep.findings if f.rule == "PC302"]

    def test_ticks_per_step_passthrough(self, compacted):
        # the facts' expected tick counts are echoed so a reader can tell
        # compaction from a broken marker chain
        assert compacted["ticks_per_step"]["w_ticks"] == 0
        assert compacted["ticks_per_step"]["head_ticks"] == 4


# ---------------------------------------------------------------------------
# schedule-sweep contract rules (PC302 per row, PC303 ordering, row ratchet)
# ---------------------------------------------------------------------------


def _sweep_line(rows=None, **over):
    line = {
        "metric": "pipeline_schedule_sweep", "value": 0.93,
        "unit": "interleaved_over_1f1b_step_time_ratio",
        "vs_baseline": 0.93, "device": "cpu", "seq_len": 64,
        "num_layers": 8, "pipeline_schedule": "sweep",
        "schedule_sweep": {
            "pp": 2, "nm": 16, "vp": 2,
            "interleaved_over_1f1b": 0.93,
            "rows": rows if rows is not None else [
                {"schedule": "wavefront", "ms_per_step": 1680.0,
                 "bubble_fraction_measured": 0.05,
                 "bubble_fraction_predicted": 0.0303},
                {"schedule": "1f1b", "ms_per_step": 1850.0,
                 "bubble_fraction_measured": 0.06,
                 "bubble_fraction_predicted": 0.0588},
                {"schedule": "1f1b-interleaved", "ms_per_step": 1717.0,
                 "bubble_fraction_measured": 0.05,
                 "bubble_fraction_predicted": 0.0303},
                {"schedule": "1f1b-zb", "ms_per_step": 2754.0,
                 "bubble_fraction_measured": 0.07,
                 "bubble_fraction_predicted": 0.0361},
            ],
        },
    }
    line.update(over)
    return line


class TestScheduleSweepRules:
    def test_facts_extraction_normalizes_rows(self):
        f = pc.perf_facts_from_bench(_sweep_line())
        rows = {r["schedule"]: r for r in f["schedule_sweep"]}
        assert set(rows) == {"wavefront", "1f1b", "1f1b-interleaved",
                             "1f1b-zb"}
        assert rows["1f1b"]["step_time_ms"] == pytest.approx(1850.0)
        assert rows["1f1b-interleaved"]["bubble_fraction_predicted"] == \
            pytest.approx(0.0303)

    def test_default_key_separates_sweep_from_headline(self):
        f = pc.perf_facts_from_bench(_sweep_line())
        assert pc.default_key(f) == "cpu_schedule_sweep"
        assert pc.default_key(pc.perf_facts_from_bench(_bench_line())) \
            == "tpu_v5_lite_bench"

    def _check(self, facts, noise=None):
        from neuronx_distributed_training_tpu.analysis.report import (
            AuditReport,
        )

        rep = AuditReport(config="t")
        pc.calibration_findings(facts, dict(pc.DEFAULT_NOISE, **(noise or {})),
                                rep)
        return rep

    def test_sweep_in_band_is_clean(self):
        rep = self._check(pc.perf_facts_from_bench(_sweep_line()))
        assert not rep.findings, rep.format()

    def test_pc302_fires_per_row_naming_schedule(self):
        rows = _sweep_line()["schedule_sweep"]["rows"]
        rows[2]["bubble_fraction_measured"] = 0.30  # interleaved idles
        rep = self._check(pc.perf_facts_from_bench(_sweep_line(rows=rows)))
        hits = [f for f in rep.findings if f.rule == "PC302"]
        assert len(hits) == 1
        assert hits[0].location == "1f1b-interleaved"
        assert "1f1b-interleaved" in hits[0].message

    def test_pc302_band_is_in_file_noise(self):
        rows = _sweep_line()["schedule_sweep"]["rows"]
        rows[2]["bubble_fraction_measured"] = 0.30
        rep = self._check(pc.perf_facts_from_bench(_sweep_line(rows=rows)),
                          noise={"bubble_abs": 0.5})
        assert not [f for f in rep.findings if f.rule == "PC302"]

    def test_pc303_ordering_gate(self):
        """The acceptance bar as a named finding: interleaved measuring
        slower than plain 1f1b beyond the band is an error."""
        rows = _sweep_line()["schedule_sweep"]["rows"]
        rows[2]["ms_per_step"] = 2400.0  # the lockstep-executor regression
        rep = self._check(pc.perf_facts_from_bench(_sweep_line(rows=rows)))
        hits = [f for f in rep.findings if f.rule == "PC303"]
        assert len(hits) == 1
        assert "ordering" in hits[0].message
        assert "1f1b-interleaved" in hits[0].message

    def test_pc303_within_band_is_clean(self):
        rows = _sweep_line()["schedule_sweep"]["rows"]
        rows[2]["ms_per_step"] = 1900.0  # 2.7% over, inside the 10% band
        rep = self._check(pc.perf_facts_from_bench(_sweep_line(rows=rows)))
        assert not [f for f in rep.findings if f.rule == "PC303"]

    def test_row_ratchet_pc101_names_schedule(self, tmp_path):
        old = pc.perf_facts_from_bench(_sweep_line())
        rows = _sweep_line()["schedule_sweep"]["rows"]
        rows[1]["ms_per_step"] = 9000.0  # 1f1b regressed ~5x
        new = pc.perf_facts_from_bench(_sweep_line(rows=rows))
        rep = pc.diff_facts(old, new)
        hits = [f for f in rep.findings
                if f.rule == "PC101" and f.location == "1f1b"]
        assert len(hits) == 1 and "schedule sweep" in hits[0].message

    def test_sweep_baseline_round_trip(self, tmp_path):
        facts = pc.perf_facts_from_bench(_sweep_line())
        pc.update_baseline("cpu_schedule_sweep", facts,
                           baselines_dir=tmp_path,
                           noise={"bubble_abs": 0.75})
        rep = pc.check_perf("cpu_schedule_sweep", facts,
                            baselines_dir=tmp_path)
        assert pc.verdict_of(rep) == "clean", rep.format()
        # a justified ordering regression records in-file
        rows = _sweep_line()["schedule_sweep"]["rows"]
        rows[2]["ms_per_step"] = 2400.0
        bad = pc.perf_facts_from_bench(_sweep_line(rows=rows))
        with pytest.raises(pc.PerfContractError, match="PC303"):
            pc.update_baseline("cpu_schedule_sweep", bad,
                               baselines_dir=tmp_path)

    def test_committed_sweep_baseline_exists_and_is_wide_banded(self):
        snap = pc.load_baseline("cpu_schedule_sweep")
        assert snap is not None, \
            "analysis/perf_baselines/cpu_schedule_sweep.json must be committed"
        rows = {r["schedule"]: r
                for r in (snap["facts"].get("schedule_sweep") or [])}
        assert set(rows) >= {"wavefront", "1f1b", "1f1b-interleaved",
                             "1f1b-zb"}
        # the measured ordering IS the committed claim
        assert rows["1f1b-interleaved"]["step_time_ms"] <= \
            rows["1f1b"]["step_time_ms"] * (1 + pc.DEFAULT_NOISE["sweep_order_frac"])
        # CPU lanes time-share host cores: the bubble band must be
        # explicitly widened in-file (the TPU default stays tight)
        assert snap["noise"]["bubble_abs"] > pc.DEFAULT_NOISE["bubble_abs"]
