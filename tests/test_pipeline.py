"""Pipeline parallelism: pp>1 loss/grads must match the unpipelined numerics."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.models import llama
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
from neuronx_distributed_training_tpu.parallel.pipeline import pipeline_loss, stage_layer_slice
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

import pytest as _pytest_mark

pytestmark = _pytest_mark.mark.slow  # multi-minute parity tests; CI fast tier deselects

FP32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                   softmax_dtype=jnp.float32)

CFG = llama.LlamaConfig(
    vocab_size=128,
    hidden_size=32,
    intermediate_size=64,
    num_layers=4,
    num_attention_heads=4,
    num_kv_heads=2,
    max_position_embeddings=32,
    activations_checkpoint_granularity=None,
)


def microbatches(key, nm=4, mb=4, s=16):
    ids = jax.random.randint(key, (nm, mb, s), 0, CFG.vocab_size)
    return {"input_ids": ids, "labels": ids}


def flat_batch(mbs):
    return {k: v.reshape((-1,) + v.shape[2:]) for k, v in mbs.items()}


def ref_loss(params, mbs):
    return llama.forward(params, flat_batch(mbs), CFG, FP32)[0]


def pipe_loss(params, mbs, mesh):
    embed_fn, stage_fn, loss_fn = llama.pipeline_hooks(CFG, FP32)
    return pipeline_loss(
        params, params["layers"], mbs,
        embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=loss_fn, mesh=mesh,
    )



def shard_inputs(mesh, params, mbs):
    """device_put params (pipeline specs) + microbatches onto ``mesh``."""
    specs = llama.param_specs(CFG, pipeline=True)
    ns = functools.partial(NamedSharding, mesh)
    sh_params = jax.device_put(
        params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
    )
    sh_mbs = jax.device_put(mbs, ns(P(None, ("data", "expert"))))
    return sh_params, sh_mbs


def assert_grads_close(grads, ref_grads, paths, tag=""):
    for path in paths:
        g, rg = grads, ref_grads
        for k in path:
            g, rg = g[k], rg[k]
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(rg), rtol=5e-4, atol=1e-5,
            err_msg=f"grad mismatch at {path} {tag}",
        )


class TestPipelineParity:
    def test_stage_layer_slice(self):
        assert stage_layer_slice(8, 2) == 4
        with pytest.raises(ValueError):
            stage_layer_slice(5, 2)

    @pytest.mark.parametrize("pp,tp", [(2, 1), (4, 1), (2, 2)])
    def test_loss_and_grads_match_unpipelined(self, devices8, pp, tp):
        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        mbs = microbatches(jax.random.PRNGKey(1))

        ref, ref_grads = jax.value_and_grad(ref_loss)(params, mbs)

        mesh = build_mesh(MeshConfig(
            pipeline_model_parallel_size=pp, tensor_model_parallel_size=tp))
        sh_params, sh_mbs = shard_inputs(mesh, params, mbs)
        with mesh, shd.use_mesh(mesh):
            loss, grads = jax.jit(
                jax.value_and_grad(lambda p, m: pipe_loss(p, m, mesh), argnums=0)
            )(sh_params, sh_mbs)
        np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
        assert_grads_close(grads, ref_grads, (
            ("embed", "embedding"),
            ("final_norm", "scale"),
            ("layers", "mlp", "down", "w"),
            ("layers", "attn", "qkv", "w"),
        ))

    def test_nm_not_divisible_by_pp(self, devices8):
        """nm % pp != 0: the round-robin parking/embed layout pads to
        ceil(nm/pp) slots per rank; padded rows must not leak into loss or
        grads (r4 design, reviewed-but-untested path)."""
        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        mbs = microbatches(jax.random.PRNGKey(1), nm=6)  # pp=4 -> slots=2, 2 pads

        ref, ref_grads = jax.value_and_grad(ref_loss)(params, mbs)
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=4))
        sh_params, sh_mbs = shard_inputs(mesh, params, mbs)
        with mesh, shd.use_mesh(mesh):
            loss, grads = jax.jit(
                jax.value_and_grad(lambda p, m: pipe_loss(p, m, mesh), argnums=0)
            )(sh_params, sh_mbs)
        np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)
        assert_grads_close(
            grads, ref_grads,
            (("embed", "embedding"), ("layers", "mlp", "down", "w")),
            tag="(nm=6, pp=4)",
        )

    def test_forward_collective_budget(self, devices8):
        """Regression guard on the wavefront's comm schedule: the FORWARD
        pipeline at pp=4/tp=1 compiles exactly 2*pp+1 collective-permutes
        (the ring hop, plus one instruction per switch branch for the
        tick-uniform embed route and parked route).  On new jax
        (partial-auto shard_map) NO all-gather is permitted at all; on the
        legacy fully-manual fallback exactly one is — the in-spec
        re-replication of the pipe-sharded embed feed over the auto axes,
        an inherent (documented) cost of that fallback, not a schedule
        regression."""
        from neuronx_distributed_training_tpu.utils.debug import collective_counts

        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        mbs = microbatches(jax.random.PRNGKey(1))
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=4))
        sh_params, sh_mbs = shard_inputs(mesh, params, mbs)
        with mesh, shd.use_mesh(mesh):
            f = jax.jit(lambda p, m: pipe_loss(p, m, mesh))
            counts = collective_counts(f, sh_params, sh_mbs)
        assert counts["collective-permute"] == 2 * 4 + 1, counts
        gather_budget = 0 if hasattr(jax, "shard_map") else 1
        assert counts["all-gather"] <= gather_budget, counts

    def test_pp1_fallback_matches(self):
        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        mbs = microbatches(jax.random.PRNGKey(1))
        ref = ref_loss(params, mbs)
        loss = pipe_loss(params, mbs, None)
        np.testing.assert_allclose(float(loss), float(ref), rtol=1e-6)

    def test_loss_mask_weighting(self, devices8):
        """Masked tokens must drop out of the pipelined global mean exactly."""
        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        mbs = microbatches(jax.random.PRNGKey(1))
        mask = np.ones(mbs["input_ids"].shape, np.float32)
        mask[0, :, :8] = 0.0  # mask half of microbatch 0
        mbs["loss_mask"] = jnp.asarray(mask)

        ref = ref_loss(params, mbs)
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
        specs = llama.param_specs(CFG, pipeline=True)
        ns = functools.partial(NamedSharding, mesh)
        sh_params = jax.device_put(
            params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
        )
        with mesh, shd.use_mesh(mesh):
            loss = jax.jit(lambda p, m: pipe_loss(p, m, mesh))(sh_params, mbs)
        np.testing.assert_allclose(float(loss), float(ref), rtol=1e-5)


class TestVirtualPipeline:
    @pytest.mark.parametrize("pp,vp", [(2, 2), (4, 2)])
    def test_vpp_matches_unpipelined(self, devices8, pp, vp):
        """Interleaved schedule (vp chunks per rank) must match plain numerics."""
        import dataclasses

        from neuronx_distributed_training_tpu.parallel.pipeline import to_interleaved

        cfg = dataclasses.replace(CFG, num_layers=pp * vp)
        params = llama.init_params(jax.random.PRNGKey(0), cfg, FP32)
        mbs = microbatches(jax.random.PRNGKey(1))

        def ref_loss_local(p, m):
            return llama.forward(p, flat_batch(m), cfg, FP32)[0]

        ref, ref_grads = jax.value_and_grad(ref_loss_local)(params, mbs)

        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=pp))
        embed_fn, stage_fn, loss_fn = llama.pipeline_hooks(cfg, FP32)

        def vpp_loss(p, m):
            inter = to_interleaved(p["layers"], pp, vp)
            return pipeline_loss(
                p, inter, m, embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=loss_fn,
                mesh=mesh, virtual_pipeline_size=vp,
            )

        ns = functools.partial(NamedSharding, mesh)
        # layers replicated here ([L] stacked); the interleave happens in-jit.
        sh_params = jax.device_put(params, ns(P()))
        sh_mbs = jax.device_put(mbs, ns(P(None, ("data", "expert"))))
        with mesh, shd.use_mesh(mesh):
            loss, grads = jax.jit(jax.value_and_grad(vpp_loss, argnums=0))(
                sh_params, sh_mbs
            )
        np.testing.assert_allclose(float(loss), float(ref), rtol=2e-5)
        for path in (("embed", "embedding"), ("layers", "attn", "qkv", "w")):
            g, rg = grads, ref_grads
            for k in path:
                g, rg = g[k], rg[k]
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(rg), rtol=5e-4, atol=1e-5,
                err_msg=f"grad mismatch at {path}",
            )

    def test_mixtral_pp2_matches_per_microbatch_reference(self, devices8):
        """Mixtral under pp=2: lm loss + psum'd router aux must equal the mean
        of per-microbatch unpipelined forwards (routing is per-microbatch, so
        that — not the flat-batch forward — is the exact reference)."""
        import dataclasses

        from neuronx_distributed_training_tpu.models import mixtral
        from neuronx_distributed_training_tpu.ops import moe as moe_ops

        cfg = mixtral.MixtralConfig(
            llama=dataclasses.replace(CFG, num_layers=4),
            moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True,
                                  router_aux_loss_coef=0.02),
        )
        params = mixtral.init_params(jax.random.PRNGKey(0), cfg, FP32)
        mbs = microbatches(jax.random.PRNGKey(1))
        nm = mbs["input_ids"].shape[0]

        def ref(p, m):
            def body(acc, mb):
                loss, _ = mixtral.forward(p, mb, cfg, FP32)
                return acc + loss, None

            total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), m)
            return total / nm

        ref_l, ref_g = jax.value_and_grad(ref)(params, mbs)

        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
        embed_fn, stage_fn, loss_fn = mixtral.pipeline_hooks(cfg, FP32)

        def pl(p, m):
            return pipeline_loss(
                p, p["layers"], m,
                embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=loss_fn,
                mesh=mesh, stage_aux=True,
                aux_scale=1.0 / (nm * cfg.num_layers),
            )

        specs = mixtral.param_specs(cfg, pipeline=True)
        ns = functools.partial(NamedSharding, mesh)
        sh_params = jax.device_put(
            params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
        )
        with mesh, shd.use_mesh(mesh):
            loss, grads = jax.jit(jax.value_and_grad(pl, argnums=0))(sh_params, mbs)
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-5)
        for path in (
            ("layers", "mlp", "router", "w"),
            ("layers", "mlp", "experts", "gate_up"),
            ("embed", "embedding"),
        ):
            g, rg = grads, ref_g
            for k in path:
                g, rg = g[k], rg[k]
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(rg), rtol=5e-4, atol=1e-5,
                err_msg=f"grad mismatch at {path}",
            )

    def test_gpt_pp2_matches_unpipelined(self, devices8):
        """Megatron GPT (learned-abs pos, layernorm+bias, gelu, tied head)
        under pp=2 matches the flat-batch forward."""
        from neuronx_distributed_training_tpu.models import gpt

        cfg = gpt.GPTConfig(
            vocab_size=128, hidden_size=32, num_layers=4, num_attention_heads=4,
            max_position_embeddings=32, position_embedding_type="learned_absolute",
            activations_checkpoint_granularity=None,
        )
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        mbs = microbatches(jax.random.PRNGKey(1))

        def ref(p, m):
            return gpt.forward(p, flat_batch(m), cfg, FP32)[0]

        ref_l, ref_g = jax.value_and_grad(ref)(params, mbs)

        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
        embed_fn, stage_fn, loss_fn = gpt.pipeline_hooks(cfg, FP32)

        def pl(p, m):
            return pipeline_loss(
                p, p["layers"], m,
                embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=loss_fn,
                mesh=mesh, stage_aux=True, aux_scale=0.0,
            )

        specs = gpt.param_specs(cfg, pipeline=True)
        ns = functools.partial(NamedSharding, mesh)
        sh_params = jax.device_put(
            params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
        )
        with mesh, shd.use_mesh(mesh):
            loss, grads = jax.jit(jax.value_and_grad(pl, argnums=0))(sh_params, mbs)
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-5)
        for path in (("embed", "embedding"), ("layers", "attn", "qkv", "w"),
                     ("pos_embed", "embedding")):
            g, rg = grads, ref_g
            for k in path:
                g, rg = g[k], rg[k]
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(rg), rtol=5e-4, atol=1e-5,
                err_msg=f"grad mismatch at {path}",
            )

    def test_gpt_pp2_dropout_runs(self, devices8):
        """Dropout under pp: per-microbatch _rng keys thread through stages."""
        from neuronx_distributed_training_tpu.models import gpt

        cfg = gpt.GPTConfig(
            vocab_size=128, hidden_size=32, num_layers=4, num_attention_heads=4,
            max_position_embeddings=32, hidden_dropout=0.1, embedding_dropout=0.1,
            activations_checkpoint_granularity=None,
        )
        params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
        mbs = dict(microbatches(jax.random.PRNGKey(1)))
        mbs["_rng"] = jax.random.split(jax.random.PRNGKey(7), 4)

        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
        embed_fn, stage_fn, loss_fn = gpt.pipeline_hooks(cfg, FP32)

        def pl(p, m):
            return pipeline_loss(
                p, p["layers"], m,
                embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=loss_fn,
                mesh=mesh, stage_aux=True,
            )

        specs = gpt.param_specs(cfg, pipeline=True)
        ns = functools.partial(NamedSharding, mesh)
        sh_params = jax.device_put(
            params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
        )
        with mesh, shd.use_mesh(mesh):
            loss, grads = jax.jit(jax.value_and_grad(pl, argnums=0))(sh_params, mbs)
        assert np.isfinite(float(loss))
        assert np.all(np.isfinite(np.asarray(grads["layers"]["attn"]["qkv"]["w"])))


class TestPreferencePipeline:
    """DPO/ORPO under pp via the concatenated forward (reference base_dpo.py:68-88)."""

    def _pref_mbs(self, key, nm=2, mb=4, s=16):
        kc, kr = jax.random.split(key)
        return {
            "chosen_input_ids": jax.random.randint(kc, (nm, mb, s), 0, CFG.vocab_size),
            "rejected_input_ids": jax.random.randint(kr, (nm, mb, s), 0, CFG.vocab_size),
        }

    @pytest.mark.parametrize("mode", ["dpo", "orpo"])
    def test_pp2_matches_direct_loss(self, devices8, mode):
        from neuronx_distributed_training_tpu.alignment.dpo import (
            make_dpo_loss_fn,
            preference_pipeline_hooks,
        )
        from neuronx_distributed_training_tpu.alignment.orpo import make_orpo_loss_fn
        from neuronx_distributed_training_tpu.ops import norm as norm_ops

        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        mbs = self._pref_mbs(jax.random.PRNGKey(1))
        nm = mbs["chosen_input_ids"].shape[0]
        if mode == "dpo":
            mbs["reference_chosen_logps"] = -5.0 * jnp.ones((nm, 4))
            mbs["reference_rejected_logps"] = -6.0 * jnp.ones((nm, 4))

        def fwd(p, batch):
            return llama.forward(p, batch, CFG, FP32)[0]  # no labels -> logits

        direct = (make_dpo_loss_fn(fwd, beta=0.1) if mode == "dpo"
                  else make_orpo_loss_fn(fwd, beta=0.1))

        def ref(p, m):
            def body(acc, mb):
                loss, _ = direct(p, mb, None)
                return acc + loss, None

            total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), m)
            return total / nm

        ref_l, ref_g = jax.value_and_grad(ref)(params, mbs)

        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
        base_embed, base_stage, _ = llama.pipeline_hooks(CFG, FP32)

        def head_fn(p, y):
            h = norm_ops.apply_rms_norm(p["final_norm"], y, eps=CFG.rms_norm_eps)
            return llama.logits_fn(p, h, CFG, FP32)

        embed_fn, stage_fn, loss_fn = preference_pipeline_hooks(
            base_embed, base_stage, head_fn, mode=mode, beta=0.1
        )

        def pl(p, m):
            return pipeline_loss(
                p, p["layers"], m,
                embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=loss_fn, mesh=mesh,
            )

        specs = llama.param_specs(CFG, pipeline=True)
        ns = functools.partial(NamedSharding, mesh)
        sh_params = jax.device_put(
            params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
        )
        with mesh, shd.use_mesh(mesh):
            loss, grads = jax.jit(jax.value_and_grad(pl, argnums=0))(sh_params, mbs)
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-5)
        for path in (("embed", "embedding"), ("layers", "attn", "qkv", "w")):
            g, rg = grads, ref_g
            for k in path:
                g, rg = g[k], rg[k]
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(rg), rtol=5e-4, atol=1e-5,
                err_msg=f"grad mismatch at {path}",
            )

    def test_interleave_round_trip(self):
        from neuronx_distributed_training_tpu.parallel.pipeline import (
            from_interleaved,
            to_interleaved,
        )

        x = {"w": jnp.arange(24.0).reshape(8, 3)}
        inter = to_interleaved(x, pp=2, vp=2)
        assert inter["w"].shape == (2, 2, 2, 3)
        # stage s = c*pp + r covers layers [s*Lc, (s+1)*Lc)
        np.testing.assert_array_equal(
            np.asarray(inter["w"][1, 0]), np.asarray(x["w"][4:6])  # chunk1 rank0 = stage2
        )
        back = from_interleaved(inter)
        np.testing.assert_array_equal(np.asarray(back["w"]), np.asarray(x["w"]))


def test_mixtral_interleaved_pp2_matches_reference(devices8):
    """moe_frequency=2 under pp=2: grouped stage slicing (whole MoE+dense
    groups per rank) matches the per-microbatch unpipelined forward."""
    import dataclasses

    from neuronx_distributed_training_tpu.models import mixtral
    from neuronx_distributed_training_tpu.ops import moe as moe_ops

    cfg = mixtral.MixtralConfig(
        llama=dataclasses.replace(CFG, num_layers=8),
        moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True,
                              router_aux_loss_coef=0.02),
        moe_frequency=2,
    )
    params = mixtral.init_params(jax.random.PRNGKey(0), cfg, FP32)
    mbs = microbatches(jax.random.PRNGKey(1))
    nm = mbs["input_ids"].shape[0]

    def ref(p, m):
        def body(acc, mb):
            loss, _ = mixtral.forward(p, mb, cfg, FP32)
            return acc + loss, None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), m)
        return total / nm

    ref_l, ref_g = jax.value_and_grad(ref)(params, mbs)

    mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
    embed_fn, stage_fn, loss_fn = mixtral.pipeline_hooks(cfg, FP32)

    def pl(p, m):
        return pipeline_loss(
            p, p["layers"], m,
            embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=loss_fn,
            mesh=mesh, stage_aux=True,
            aux_scale=1.0 / (nm * mixtral.num_moe_layers(cfg)),
        )

    specs = mixtral.param_specs(cfg, pipeline=True)
    ns = functools.partial(NamedSharding, mesh)
    sh_params = jax.device_put(
        params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
    )
    with mesh, shd.use_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(pl, argnums=0))(sh_params, mbs)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-5)
    for path in (("layers", "mlp", "moe", "router", "w"),
                 ("layers", "mlp", "dense", "gate_up", "w"),
                 ("embed", "embedding")):
        g, rg = grads, ref_g
        for k in path:
            g, rg = g[k], rg[k]
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(rg), rtol=5e-4, atol=1e-5,
            err_msg=f"grad mismatch at {path}",
        )


def test_chunked_ce_pp2_matches(devices8):
    """fusions.chunked_ce in the PP loss hook: numerics identical to the
    standard logits path."""
    import dataclasses

    params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
    mbs = microbatches(jax.random.PRNGKey(1))
    mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
    specs = llama.param_specs(CFG, pipeline=True)
    ns = functools.partial(NamedSharding, mesh)
    sh_params = jax.device_put(
        params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
    )

    def pl(cfg):
        embed_fn, stage_fn, loss_fn = llama.pipeline_hooks(cfg, FP32)

        def f(p, m):
            return pipeline_loss(
                p, p["layers"], m,
                embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=loss_fn, mesh=mesh,
            )

        return f

    with mesh, shd.use_mesh(mesh):
        ref, ref_g = jax.jit(jax.value_and_grad(pl(CFG)))(sh_params, mbs)
        cfg2 = dataclasses.replace(CFG, vocab_chunks=4)
        got, got_g = jax.jit(jax.value_and_grad(pl(cfg2)))(sh_params, mbs)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(got_g["embed"]["embedding"]),
        np.asarray(ref_g["embed"]["embedding"]), rtol=5e-4, atol=1e-6)


def test_gpt_interleaved_pp2_matches_reference(devices8):
    """GPT moe_frequency=2 under pp=2: grouped stage slicing (whole MoE+dense
    groups per rank) matches the per-microbatch unpipelined forward — the GPT
    mirror of the mixtral interleave test."""
    from neuronx_distributed_training_tpu.models import gpt
    from neuronx_distributed_training_tpu.ops import moe as moe_ops

    cfg = gpt.GPTConfig(
        vocab_size=128, hidden_size=32, num_layers=8, num_attention_heads=4,
        max_position_embeddings=32, normalization="rmsnorm", bias=False,
        activation="swiglu", ffn_hidden_size=64,
        activations_checkpoint_granularity=None,
        moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True,
                              router_aux_loss_coef=0.02),
        moe_frequency=2,
    )
    params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
    mbs = microbatches(jax.random.PRNGKey(1))
    nm = mbs["input_ids"].shape[0]

    def ref(p, m):
        def body(acc, mb):
            loss, _ = gpt.forward(p, mb, cfg, FP32)
            return acc + loss, None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), m)
        return total / nm

    ref_l, ref_g = jax.value_and_grad(ref)(params, mbs)

    mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
    embed_fn, stage_fn, loss_fn = gpt.pipeline_hooks(cfg, FP32)

    def pl(p, m):
        return pipeline_loss(
            p, p["layers"], m,
            embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=loss_fn,
            mesh=mesh, stage_aux=True,
            aux_scale=1.0 / (nm * gpt.num_moe_layers(cfg)),
        )

    specs = gpt.param_specs(cfg, pipeline=True)
    ns = functools.partial(NamedSharding, mesh)
    sh_params = jax.device_put(
        params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
    )
    with mesh, shd.use_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(pl, argnums=0))(sh_params, mbs)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-5)
    for path in (("layers", "mlp", "moe", "router", "w"),
                 ("layers", "mlp", "dense", "up", "w"),
                 ("layers", "attn", "qkv", "w"),
                 ("embed", "embedding")):
        g, rg = grads, ref_g
        for k in path:
            g, rg = g[k], rg[k]
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(rg), rtol=5e-4, atol=1e-5,
            err_msg=f"grad mismatch at {path}",
        )


def test_gpt_interleaved_pp2_dropout_runs(devices8):
    """Grouped dropout-key threading ([g, f] per stage) under pp=2."""
    from neuronx_distributed_training_tpu.models import gpt
    from neuronx_distributed_training_tpu.ops import moe as moe_ops

    cfg = gpt.GPTConfig(
        vocab_size=128, hidden_size=32, num_layers=8, num_attention_heads=4,
        max_position_embeddings=32, hidden_dropout=0.1,
        activations_checkpoint_granularity=None,
        moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True),
        moe_frequency=2,
    )
    params = gpt.init_params(jax.random.PRNGKey(0), cfg, FP32)
    mbs = dict(microbatches(jax.random.PRNGKey(1)))
    mbs["_rng"] = jax.random.split(jax.random.PRNGKey(7), 4)

    mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
    embed_fn, stage_fn, loss_fn = gpt.pipeline_hooks(cfg, FP32)

    def pl(p, m):
        return pipeline_loss(
            p, p["layers"], m,
            embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=loss_fn,
            mesh=mesh, stage_aux=True,
            aux_scale=1.0 / (4 * gpt.num_moe_layers(cfg)),
        )

    specs = gpt.param_specs(cfg, pipeline=True)
    ns = functools.partial(NamedSharding, mesh)
    sh_params = jax.device_put(
        params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
    )
    with mesh, shd.use_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(pl, argnums=0))(sh_params, mbs)
    assert np.isfinite(float(loss))
    assert np.all(np.isfinite(np.asarray(grads["layers"]["mlp"]["moe"]["router"]["w"])))


def test_mixtral_interleaved_vpp_matches_reference(devices8):
    """moe_frequency=2 under pp=2 x vp=2: grouped leaves ([G]-leading moe,
    [G, f-1] dense) reshape through to_interleaved consistently with the flat
    [L] attn/norm leaves (chunk layers = Gc*f)."""
    import dataclasses

    from neuronx_distributed_training_tpu.models import mixtral
    from neuronx_distributed_training_tpu.ops import moe as moe_ops
    from neuronx_distributed_training_tpu.parallel.pipeline import to_interleaved

    cfg = mixtral.MixtralConfig(
        llama=dataclasses.replace(CFG, num_layers=8),
        moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True,
                              router_aux_loss_coef=0.02),
        moe_frequency=2,
    )
    params = mixtral.init_params(jax.random.PRNGKey(0), cfg, FP32)
    mbs = microbatches(jax.random.PRNGKey(1))
    nm = mbs["input_ids"].shape[0]

    def ref(p, m):
        def body(acc, mb):
            loss, _ = mixtral.forward(p, mb, cfg, FP32)
            return acc + loss, None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), m)
        return total / nm

    ref_l, ref_g = jax.value_and_grad(ref)(params, mbs)

    pp, vp = 2, 2
    mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=pp))
    embed_fn, stage_fn, loss_fn = mixtral.pipeline_hooks(cfg, FP32)
    inter = to_interleaved(params["layers"], pp, vp)
    p_inter = {**params, "layers": inter}

    def pl(p, m):
        return pipeline_loss(
            p, p["layers"], m,
            embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=loss_fn,
            mesh=mesh, virtual_pipeline_size=vp, stage_aux=True,
            aux_scale=1.0 / (nm * mixtral.num_moe_layers(cfg)),
        )

    specs = mixtral.param_specs(cfg, pipeline=True)
    specs["layers"] = jax.tree_util.tree_map(
        lambda s: P(None, s[0], None, *tuple(s)[1:]), specs["layers"],
        is_leaf=lambda x: isinstance(x, P),
    )
    ns = functools.partial(NamedSharding, mesh)
    sh_params = jax.device_put(
        p_inter, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
    )
    with mesh, shd.use_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(pl, argnums=0))(sh_params, mbs)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-5)
    # grads come back in the interleaved layout; compare via to_interleaved(ref)
    ref_inter = to_interleaved(
        jax.tree_util.tree_map(np.asarray, ref_g["layers"]), pp, vp)
    for path in (("mlp", "moe", "router", "w"),
                 ("mlp", "dense", "gate_up", "w"),
                 ("attn", "qkv", "w")):
        g, rg = grads["layers"], ref_inter
        for k in path:
            g, rg = g[k], rg[k]
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(rg), rtol=5e-4, atol=1e-5,
            err_msg=f"grad mismatch at {path}",
        )
    np.testing.assert_allclose(
        np.asarray(grads["embed"]["embedding"]),
        np.asarray(ref_g["embed"]["embedding"]), rtol=5e-4, atol=1e-5)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_cp_attention_under_pp_matches(devices8, impl):
    """CP attention under pipeline parallelism (the reference's 70B CP
    flagship layout, hf_llama3_70B_CP_config: TP=32 PP=8 CP=2).  Inside the
    pipe-Manual pipeline body a nested shard_map corrupts backward for
    pipe-varying inputs, so ring/ulysses route to the GSPMD blockwise body —
    loss AND grads must match the unsharded core-attention reference."""
    import dataclasses

    cfg = dataclasses.replace(
        CFG, num_layers=2, attention_impl=impl, context_parallel=True,
        max_position_embeddings=64,
    )
    ref_cfg = dataclasses.replace(CFG, num_layers=2, max_position_embeddings=64)
    params = llama.init_params(jax.random.PRNGKey(0), ref_cfg, FP32)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 2, 64), 0, CFG.vocab_size)
    mbs = {"input_ids": ids, "labels": ids}
    nm = ids.shape[0]

    def ref(p, m):
        def body(acc, mb):
            return acc + llama.forward(p, mb, ref_cfg, FP32)[0], None

        total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), m)
        return total / nm

    ref_l, ref_g = jax.value_and_grad(ref)(params, mbs)

    mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2,
                                 context_parallel_size=2,
                                 tensor_model_parallel_size=2))
    embed_fn, stage_fn, loss_fn = llama.pipeline_hooks(cfg, FP32)

    def pl(p, m):
        return pipeline_loss(
            p, p["layers"], m,
            embed_fn=embed_fn, stage_fn=stage_fn, loss_fn=loss_fn, mesh=mesh,
        )

    specs = llama.param_specs(cfg, pipeline=True)
    ns = functools.partial(NamedSharding, mesh)
    sh_params = jax.device_put(
        params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
    )
    sh_mbs = jax.device_put(mbs, ns(P(None, ("data", "expert"), "context")))
    with mesh, shd.use_mesh(mesh):
        loss, grads = jax.jit(jax.value_and_grad(pl, argnums=0))(sh_params, sh_mbs)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-5)
    for path in (("embed", "embedding"), ("layers", "attn", "qkv", "w")):
        g, rg = grads, ref_g
        for k in path:
            g, rg = g[k], rg[k]
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(rg), rtol=5e-4, atol=1e-5,
            err_msg=f"grad mismatch at {path}",
        )
