"""1F1B pipeline schedule: gate, parity, return contract, memory bound.

The manual-vjp 1F1B (``parallel.pipeline.pipeline_loss_and_grad``) is the
production PP path whenever ``supports_1f1b`` allows.  Manual-vjp schedules
are exactly the code class that silently diverges from autodiff, so this file
runs FAST (not ``slow``): loss/grad parity against the autodiff wavefront is
exercised on every tier-1 run on the 8-device CPU mesh.

The memory test pins the schedule's reason to exist: compiled peak temp
memory of the 1F1B step grows sub-linearly in num_microbatches (only the
pre-computed embed feed and its cotangent scale with nm, ~1 activation per
microbatch per pipe rank), while the autodiff wavefront retains ~2
activation-sized residuals per microbatch (the per-tick stage-input saves
plus the parked/head chain) — the O(pp) vs O(nm + pp) divide at scale.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.models import llama
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
from neuronx_distributed_training_tpu.parallel.pipeline import (
    PIPELINE_SCHEDULES,
    pipeline_loss,
    pipeline_loss_and_grad,
    resolve_schedule,
    supports_1f1b,
)
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

FP32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                   softmax_dtype=jnp.float32)

CFG = llama.LlamaConfig(
    vocab_size=128,
    hidden_size=32,
    intermediate_size=64,
    num_layers=4,
    num_attention_heads=4,
    num_kv_heads=2,
    max_position_embeddings=32,
    activations_checkpoint_granularity=None,
)

GRAD_PATHS = (
    ("layers", "mlp", "down", "w"),
    ("layers", "attn", "qkv", "w"),
    ("layers", "input_norm", "scale"),
)


def _pcfg(pp=2, vp=1, alignment=None, lora=False):
    return {
        "pipeline_model_parallel_size": pp,
        "virtual_pipeline_model_parallel_size": vp,
        "alignment": alignment,
        "lora": lora,
    }


def microbatches(key, nm=4, mb=4, s=16, vocab=128):
    ids = jax.random.randint(key, (nm, mb, s), 0, vocab)
    return {"input_ids": ids, "labels": ids}


def shard_for(mesh, cfg, params, mbs, specs=None):
    specs = specs if specs is not None else llama.param_specs(cfg, pipeline=True)
    ns = functools.partial(NamedSharding, mesh)
    sh_params = jax.device_put(
        params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
    )
    sh_mbs = jax.device_put(mbs, ns(P(None, ("data", "expert"))))
    return sh_params, sh_mbs


def wavefront_loss_and_grad(mesh, hooks, params, mbs, **kw):
    embed_fn, stage_fn, loss_fn = hooks

    def wf(p, m):
        return pipeline_loss(
            p, p["layers"], m, embed_fn=embed_fn, stage_fn=stage_fn,
            loss_fn=loss_fn, mesh=mesh, **kw,
        )

    with mesh, shd.use_mesh(mesh):
        return jax.jit(jax.value_and_grad(wf))(params, mbs)


def onef1b_loss_and_grad(mesh, cfg, hooks, params, mbs, **kw):
    embed_fn, stage_fn, _ = hooks
    hh, hp_of, hw_of, _fold = llama.onef1b_head_hooks(cfg, FP32)

    def f1b(p, m):
        return pipeline_loss_and_grad(
            p, p["layers"], m, embed_fn=embed_fn, stage_fn=stage_fn,
            head_hidden_fn=hh, head_params=hp_of(p), head_weight=hw_of(p),
            mesh=mesh, **kw,
        )

    with mesh, shd.use_mesh(mesh):
        return jax.jit(f1b)(params, mbs)


def assert_path_close(got, want, paths, rtol=5e-4, atol=1e-5, tag=""):
    for path in paths:
        a, b = got, want
        for k in path:
            a, b = a[k], b[k]
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=rtol, atol=atol,
            err_msg=f"grad mismatch at {path} {tag}",
        )


class TestSupports1F1B:
    """The schedule gate, combination by combination."""

    def test_llama_pp2_supported(self):
        ok, reason = supports_1f1b(CFG, _pcfg(pp=2))
        assert ok, reason

    def test_pp1_unsupported(self):
        ok, reason = supports_1f1b(CFG, _pcfg(pp=1))
        assert not ok and "pipeline_model_parallel_size" in reason

    def test_vp_unsupported(self):
        ok, reason = supports_1f1b(CFG, _pcfg(pp=2, vp=2))
        assert not ok and "virtual" in reason

    def test_cp_unsupported(self):
        pcfg = dict(_pcfg(pp=2), context_parallel_size=2)
        ok, reason = supports_1f1b(CFG, pcfg)
        assert not ok and "context" in reason
        assert resolve_schedule("auto", CFG, pcfg) == "wavefront"

    @pytest.mark.parametrize("alignment", ["dpo", "orpo", "kto"])
    def test_preference_alignment_unsupported(self, alignment):
        ok, reason = supports_1f1b(CFG, _pcfg(pp=2, alignment=alignment))
        assert not ok and alignment in reason

    def test_sft_alignment_supported(self):
        ok, _ = supports_1f1b(CFG, _pcfg(pp=2, alignment="sft"))
        assert ok

    def test_lora_unsupported(self):
        ok, reason = supports_1f1b(CFG, _pcfg(pp=2, lora=True))
        assert not ok and "LoRA" in reason

    def test_gpt_unsupported(self):
        from neuronx_distributed_training_tpu.models import gpt

        gc = gpt.GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                           num_attention_heads=4, max_position_embeddings=32)
        ok, reason = supports_1f1b(gc, _pcfg(pp=2))
        assert not ok and "GPTConfig" in reason

    def test_mixtral_unsupported_keeps_wavefront(self):
        """Dropless-MoE stage vjp has backend-dependent numerics inside the
        1f1b tick loop (bisected: loss exact, stage grads off by a few
        percent under the legacy fully-manual shard_map fallback), so the
        gate keeps mixtral on the autodiff wavefront — and ``auto`` must
        resolve there rather than erroring."""
        import dataclasses

        from neuronx_distributed_training_tpu.models import mixtral
        from neuronx_distributed_training_tpu.ops import moe as moe_ops

        xc = mixtral.MixtralConfig(
            llama=dataclasses.replace(CFG),
            moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True),
        )
        ok, reason = supports_1f1b(xc, _pcfg(pp=2))
        assert not ok and "mixtral" in reason
        assert resolve_schedule("auto", xc, _pcfg(pp=2)) == "wavefront"
        with pytest.raises(ValueError, match="mixtral"):
            resolve_schedule("1f1b", xc, _pcfg(pp=2))

    def test_zigzag_unsupported(self):
        import dataclasses

        zz = dataclasses.replace(CFG, attention_impl="zigzag_ring")
        ok, reason = supports_1f1b(zz, _pcfg(pp=2))
        assert not ok and "zigzag" in reason


class TestResolveSchedule:
    def test_auto_picks_1f1b_when_supported(self):
        assert resolve_schedule("auto", CFG, _pcfg(pp=2)) == "1f1b"

    def test_auto_falls_back_to_wavefront(self):
        assert resolve_schedule("auto", CFG, _pcfg(pp=2, vp=2)) == "wavefront"

    def test_forced_wavefront_always_wins(self):
        assert resolve_schedule("wavefront", CFG, _pcfg(pp=2)) == "wavefront"

    def test_forced_1f1b_on_supported(self):
        assert resolve_schedule("1f1b", CFG, _pcfg(pp=2)) == "1f1b"

    def test_forced_1f1b_on_unsupported_raises_with_reason(self):
        with pytest.raises(ValueError, match="virtual"):
            resolve_schedule("1f1b", CFG, _pcfg(pp=2, vp=2))
        with pytest.raises(ValueError, match="dpo"):
            resolve_schedule("1f1b", CFG, _pcfg(pp=2, alignment="dpo"))

    def test_unknown_schedule_rejected(self):
        with pytest.raises(ValueError, match="pipeline.schedule"):
            resolve_schedule("gpipe", CFG, _pcfg(pp=2))
        assert PIPELINE_SCHEDULES == ("auto", "1f1b", "wavefront")

    def test_default_none_means_auto(self):
        assert resolve_schedule(None, CFG, _pcfg(pp=2)) == "1f1b"


class TestParity:
    """1F1B loss and ALL grad families must match wavefront + jax.grad —
    the feature-defining test for a manual-vjp schedule."""

    @pytest.mark.parametrize("tied", [False, True])
    def test_pp2_loss_and_grads_match_wavefront(self, devices8, tied):
        import dataclasses

        cfg = dataclasses.replace(CFG, tie_word_embeddings=tied)
        params = llama.init_params(jax.random.PRNGKey(0), cfg, FP32)
        mbs = microbatches(jax.random.PRNGKey(1))
        hooks = llama.pipeline_hooks(cfg, FP32)
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
        sh_params, sh_mbs = shard_for(mesh, cfg, params, mbs)

        ref_l, ref_g = wavefront_loss_and_grad(mesh, hooks, sh_params, sh_mbs)
        loss, g = onef1b_loss_and_grad(mesh, cfg, hooks, sh_params, sh_mbs)

        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        assert_path_close(g["layers"], ref_g["layers"],
                          tuple(p[1:] for p in GRAD_PATHS), tag=f"(tied={tied})")
        np.testing.assert_allclose(
            np.asarray(g["head_params"]["final_norm"]["scale"]),
            np.asarray(ref_g["final_norm"]["scale"]), rtol=5e-4, atol=1e-5)
        d_embed = np.asarray(g["params_from_embed"]["embed"]["embedding"])
        if tied:
            # tied head: embed grad = embed-path cotangent + head matmul grad
            np.testing.assert_allclose(
                d_embed + np.asarray(g["head_weight"]),
                np.asarray(ref_g["embed"]["embedding"]), rtol=5e-4, atol=1e-5)
        else:
            np.testing.assert_allclose(
                d_embed, np.asarray(ref_g["embed"]["embedding"]),
                rtol=5e-4, atol=1e-5)
            np.testing.assert_allclose(
                np.asarray(g["head_weight"]).T,
                np.asarray(ref_g["lm_head"]["w"]), rtol=5e-4, atol=1e-5)

    def test_pp4_nm_not_divisible(self, devices8):
        """nm % pp != 0: padded embed-feed/cotangent slots must not leak."""
        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        mbs = microbatches(jax.random.PRNGKey(1), nm=6)  # pp=4 -> 2 pad rows
        hooks = llama.pipeline_hooks(CFG, FP32)
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=4))
        sh_params, sh_mbs = shard_for(mesh, CFG, params, mbs)

        ref_l, ref_g = wavefront_loss_and_grad(mesh, hooks, sh_params, sh_mbs)
        loss, g = onef1b_loss_and_grad(mesh, CFG, hooks, sh_params, sh_mbs)
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(g["params_from_embed"]["embed"]["embedding"]),
            np.asarray(ref_g["embed"]["embedding"]), rtol=5e-4, atol=1e-5,
            err_msg="(nm=6, pp=4)")
        assert_path_close(g["layers"], ref_g["layers"],
                          (("mlp", "down", "w"),), tag="(nm=6, pp=4)")

    def test_loss_mask_weighting(self, devices8):
        """Masked tokens drop out of loss AND denominator identically."""
        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        mbs = dict(microbatches(jax.random.PRNGKey(1)))
        mask = np.ones(np.asarray(mbs["input_ids"]).shape, np.float32)
        mask[0, :, :8] = 0.0
        mbs["loss_mask"] = jnp.asarray(mask)
        hooks = llama.pipeline_hooks(CFG, FP32)
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
        sh_params, sh_mbs = shard_for(mesh, CFG, params, mbs)

        ref_l, _ = wavefront_loss_and_grad(mesh, hooks, sh_params, sh_mbs)
        loss, _ = onef1b_loss_and_grad(mesh, CFG, hooks, sh_params, sh_mbs)
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)

    def test_return_contract(self, devices8):
        """The documented grads contract is a tested invariant: exactly the
        keys {layers, params_from_embed, head_params, head_weight}, with
        params_from_embed shaped like the FULL params tree (vjp applied
        internally — not a raw embed-feed cotangent)."""
        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        mbs = microbatches(jax.random.PRNGKey(1), nm=2)
        hooks = llama.pipeline_hooks(CFG, FP32)
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
        sh_params, sh_mbs = shard_for(mesh, CFG, params, mbs)
        _, g = onef1b_loss_and_grad(mesh, CFG, hooks, sh_params, sh_mbs)
        assert sorted(g) == ["head_params", "head_weight", "layers",
                             "params_from_embed"]
        assert (jax.tree_util.tree_structure(g["params_from_embed"])
                == jax.tree_util.tree_structure(params))
        same_shapes = jax.tree_util.tree_map(
            lambda a, b: a.shape == b.shape, g["params_from_embed"], params)
        assert all(jax.tree_util.tree_leaves(same_shapes))
        # head grads cover the head param subtree, vocab-major head weight
        assert sorted(g["head_params"]) == ["final_norm"]
        V, H = CFG.vocab_size, CFG.hidden_size
        assert g["head_weight"].shape == (V, H)

    def test_pp1_raises(self):
        hooks = llama.pipeline_hooks(CFG, FP32)
        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        mbs = microbatches(jax.random.PRNGKey(1), nm=2)
        embed_fn, stage_fn, _ = hooks
        hh, hp_of, hw_of, _fold = llama.onef1b_head_hooks(CFG, FP32)
        with pytest.raises(ValueError, match="pp > 1"):
            pipeline_loss_and_grad(
                params, params["layers"], mbs, embed_fn=embed_fn,
                stage_fn=stage_fn, head_hidden_fn=hh,
                head_params=hp_of(params), head_weight=hw_of(params),
                mesh=None)


class TestMemoryBound:
    """The schedule's reason to exist, pinned via compiled memory analysis.

    Marginal temp bytes per extra microbatch: the wavefront retains ~2
    activation-sized residuals per microbatch (per-tick stage-input saves +
    the parked/head chain), the 1F1B only the embed feed + its cotangent
    (~1 activation per microbatch per rank) on top of its O(pp) in-flight
    ring.  Measured at nm ∈ {2, 8} on the pp=2 mesh."""

    def test_1f1b_temp_memory_sublinear_in_nm(self, devices8):
        import dataclasses

        from tests.conftest import lower_in_mesh

        cfg = dataclasses.replace(
            CFG, vocab_size=64, hidden_size=256, intermediate_size=256,
            num_attention_heads=2, num_kv_heads=2, max_position_embeddings=128,
        )
        mb, s = 8, 128
        act_bytes = mb * s * cfg.hidden_size * 4  # one fp32 microbatch act
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
        params = llama.init_params(jax.random.PRNGKey(0), cfg, FP32)
        embed_fn, stage_fn, loss_fn = llama.pipeline_hooks(cfg, FP32)
        hh, hp_of, hw_of, _fold = llama.onef1b_head_hooks(cfg, FP32)

        def wf(p, m):
            return pipeline_loss(p, p["layers"], m, embed_fn=embed_fn,
                                 stage_fn=stage_fn, loss_fn=loss_fn, mesh=mesh)

        def f1b(p, m):
            return pipeline_loss_and_grad(
                p, p["layers"], m, embed_fn=embed_fn, stage_fn=stage_fn,
                head_hidden_fn=hh, head_params=hp_of(p), head_weight=hw_of(p),
                mesh=mesh)

        temps = {}
        for nm in (2, 8):
            mbs = microbatches(jax.random.PRNGKey(1), nm=nm, mb=mb, s=s,
                               vocab=cfg.vocab_size)
            sh_params, sh_mbs = shard_for(mesh, cfg, params, mbs)
            temps[nm] = (
                lower_in_mesh(mesh, jax.value_and_grad(wf), sh_params, sh_mbs)
                .memory_analysis().temp_size_in_bytes,
                lower_in_mesh(mesh, f1b, sh_params, sh_mbs)
                .memory_analysis().temp_size_in_bytes,
            )
        wf_slope = (temps[8][0] - temps[2][0]) / 6.0
        f1b_slope = (temps[8][1] - temps[2][1]) / 6.0
        detail = {
            "temps": {k: tuple(int(x) for x in v) for k, v in temps.items()},
            "act_bytes": act_bytes,
            "wf_bytes_per_mb": wf_slope, "f1b_bytes_per_mb": f1b_slope,
        }
        # wavefront ~linear: >= 1.4 activation-sized residuals per microbatch
        assert wf_slope >= 1.4 * act_bytes, detail
        # 1F1B sub-linear: only the embed feed + cotangent scale with nm —
        # well under the wavefront's slope and ~1 activation per microbatch
        assert f1b_slope <= 0.75 * wf_slope, detail
        assert f1b_slope <= 1.25 * act_bytes, detail
        # and strictly less absolute temp memory once microbatches stack up
        assert temps[8][1] < temps[8][0], detail


class TestTrainerDispatch:
    """The trainer builds the 1F1B loss+grad when the gate fires, feeding the
    identical AdamW/ZeRO-1 + metrics + grad-pinning path — one step under
    each schedule must produce the same loss AND grad_norm."""

    def _cfg(self, schedule, arch_overrides=None):
        cfg = {
            "name": f"f1b_dispatch_{schedule}",
            "model_source": "hf",
            "seed": 0,
            "trainer": {"max_steps": 1, "log_every_n_steps": 1},
            "distributed_strategy": {
                "pipeline_model_parallel_size": 2,
                "pipeline": {"schedule": schedule},
            },
            "data": {"global_batch_size": 8, "micro_batch_size": 1,
                     "seq_length": 16, "synthetic": True},
            "model": {
                "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
                "num_layers": 4, "num_attention_heads": 4,
                "num_key_value_heads": 2, "max_position_embeddings": 32,
                "activations_checkpoint_granularity": None,
                "optim": {"name": "adamw_fp32OptState", "lr": 1e-3,
                          "sched": {"name": "constant"}},
            },
            "precision": {"type": "fp32"},
        }
        if arch_overrides:
            cfg["model"].update(arch_overrides)
        return cfg

    def _one_step(self, schedule):
        from neuronx_distributed_training_tpu.config.loader import load_config
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        t = Trainer.from_config(load_config(self._cfg(schedule)),
                                enable_checkpointing=False)
        batch = next(t.data_module.sharded_batches(t.mesh))
        with t.mesh, shd.use_mesh(t.mesh):
            _, _, metrics = t.train_step(t.params, t.opt_state, batch,
                                         jax.random.PRNGKey(0))
        return t.pipeline_schedule, {k: float(v) for k, v in metrics.items()}

    def test_schedules_produce_identical_step(self, devices8):
        sched_f, m_f = self._one_step("1f1b")
        sched_w, m_w = self._one_step("wavefront")
        assert sched_f == "1f1b" and sched_w == "wavefront"
        np.testing.assert_allclose(m_f["loss"], m_w["loss"], rtol=1e-5)
        np.testing.assert_allclose(m_f["grad_norm"], m_w["grad_norm"], rtol=1e-4)

    def test_auto_resolves_to_1f1b(self, devices8):
        from neuronx_distributed_training_tpu.config.loader import load_config
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        t = Trainer.from_config(load_config(self._cfg("auto")),
                                enable_checkpointing=False)
        assert t.pipeline_schedule == "1f1b"

    def test_forced_1f1b_on_gpt_raises(self, devices8):
        """The family gate fires at trainer build with the gate's reason —
        not deep inside shard_map."""
        from neuronx_distributed_training_tpu.config.loader import load_config
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = self._cfg("1f1b", arch_overrides={"architecture": "gpt"})
        with pytest.raises(ValueError, match="1f1b is unsupported"):
            Trainer.from_config(load_config(cfg), enable_checkpointing=False)
