"""1F1B pipeline schedule: gate, parity, return contract, memory bound.

The manual-vjp 1F1B (``parallel.pipeline.pipeline_loss_and_grad``) is the
production PP path whenever ``supports_1f1b`` allows.  Manual-vjp schedules
are exactly the code class that silently diverges from autodiff, so this file
runs FAST (not ``slow``): loss/grad parity against the autodiff wavefront is
exercised on every tier-1 run on the 8-device CPU mesh.

The memory test pins the schedule's reason to exist: compiled peak temp
memory of the 1F1B step grows sub-linearly in num_microbatches (only the
pre-computed embed feed and its cotangent scale with nm, ~1 activation per
microbatch per pipe rank), while the autodiff wavefront retains ~2
activation-sized residuals per microbatch (the per-tick stage-input saves
plus the parked/head chain) — the O(pp) vs O(nm + pp) divide at scale.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.models import llama
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
from neuronx_distributed_training_tpu.parallel.pipeline import (
    MANUAL_VJP_SCHEDULES,
    PIPELINE_SCHEDULES,
    bubble_multiplier,
    pipeline_loss,
    pipeline_loss_and_grad,
    predicted_bubble_fraction,
    resolve_schedule,
    ring_slot_counts,
    supports_1f1b,
    to_interleaved,
    work_table,
)
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

FP32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                   softmax_dtype=jnp.float32)

CFG = llama.LlamaConfig(
    vocab_size=128,
    hidden_size=32,
    intermediate_size=64,
    num_layers=4,
    num_attention_heads=4,
    num_kv_heads=2,
    max_position_embeddings=32,
    activations_checkpoint_granularity=None,
)

GRAD_PATHS = (
    ("layers", "mlp", "down", "w"),
    ("layers", "attn", "qkv", "w"),
    ("layers", "input_norm", "scale"),
)


def _pcfg(pp=2, vp=1, alignment=None, lora=False):
    return {
        "pipeline_model_parallel_size": pp,
        "virtual_pipeline_model_parallel_size": vp,
        "alignment": alignment,
        "lora": lora,
    }


def microbatches(key, nm=4, mb=4, s=16, vocab=128):
    ids = jax.random.randint(key, (nm, mb, s), 0, vocab)
    return {"input_ids": ids, "labels": ids}


def shard_for(mesh, cfg, params, mbs, specs=None, vp=1):
    specs = specs if specs is not None else llama.param_specs(cfg, pipeline=True)
    if vp > 1:
        pp = int(mesh.shape.get("pipe", 1))
        params = {**params, "layers": to_interleaved(params["layers"], pp, vp)}
        specs = dict(specs)
        specs["layers"] = jax.tree_util.tree_map(
            lambda s: P(None, s[0], None, *tuple(s)[1:]), specs["layers"],
            is_leaf=lambda x: isinstance(x, P))
    ns = functools.partial(NamedSharding, mesh)
    sh_params = jax.device_put(
        params, jax.tree_util.tree_map(ns, specs, is_leaf=lambda x: isinstance(x, P))
    )
    sh_mbs = jax.device_put(mbs, ns(P(None, ("data", "expert"))))
    return sh_params, sh_mbs


def wavefront_loss_and_grad(mesh, hooks, params, mbs, **kw):
    embed_fn, stage_fn, loss_fn = hooks

    def wf(p, m):
        return pipeline_loss(
            p, p["layers"], m, embed_fn=embed_fn, stage_fn=stage_fn,
            loss_fn=loss_fn, mesh=mesh, **kw,
        )

    with mesh, shd.use_mesh(mesh):
        return jax.jit(jax.value_and_grad(wf))(params, mbs)


def onef1b_loss_and_grad(mesh, cfg, hooks, params, mbs, **kw):
    embed_fn, stage_fn, _ = hooks
    hh, hp_of, hw_of, _fold = llama.onef1b_head_hooks(cfg, FP32)

    def f1b(p, m):
        return pipeline_loss_and_grad(
            p, p["layers"], m, embed_fn=embed_fn, stage_fn=stage_fn,
            head_hidden_fn=hh, head_params=hp_of(p), head_weight=hw_of(p),
            mesh=mesh, **kw,
        )

    with mesh, shd.use_mesh(mesh):
        return jax.jit(f1b)(params, mbs)


def assert_path_close(got, want, paths, rtol=5e-4, atol=1e-5, tag=""):
    for path in paths:
        a, b = got, want
        for k in path:
            a, b = a[k], b[k]
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=rtol, atol=atol,
            err_msg=f"grad mismatch at {path} {tag}",
        )


class TestSupports1F1B:
    """The schedule gate, combination by combination."""

    def test_llama_pp2_supported(self):
        ok, reason = supports_1f1b(CFG, _pcfg(pp=2))
        assert ok, reason

    def test_pp1_unsupported(self):
        ok, reason = supports_1f1b(CFG, _pcfg(pp=1))
        assert not ok and "pipeline_model_parallel_size" in reason

    def test_plain_1f1b_rejects_vp_naming_interleaved(self):
        """The vp>1 message points at the interleaved schedule now — not at
        the autodiff wavefront (satellite: stale-message fix)."""
        ok, reason = supports_1f1b(CFG, _pcfg(pp=2, vp=2))
        assert not ok and "1f1b-interleaved" in reason
        assert "wavefront" not in reason

    def test_interleaved_supported_with_vp(self):
        ok, reason = supports_1f1b(CFG, _pcfg(pp=2, vp=2),
                                   "1f1b-interleaved")
        assert ok, reason

    def test_interleaved_needs_vp(self):
        ok, reason = supports_1f1b(CFG, _pcfg(pp=2), "1f1b-interleaved")
        assert not ok and "nothing to interleave" in reason

    def test_zb_supported_at_vp1_only(self):
        ok, reason = supports_1f1b(CFG, _pcfg(pp=2), "1f1b-zb")
        assert ok, reason
        ok, reason = supports_1f1b(CFG, _pcfg(pp=2, vp=2), "1f1b-zb")
        assert not ok and "1f1b-interleaved" in reason

    @pytest.mark.parametrize("sched", MANUAL_VJP_SCHEDULES)
    def test_cp_blocks_every_manual_vjp_schedule(self, sched):
        pcfg = dict(_pcfg(pp=2, vp=2 if sched == "1f1b-interleaved" else 1),
                    context_parallel_size=2)
        ok, reason = supports_1f1b(CFG, pcfg, sched)
        assert not ok and "context" in reason

    def test_non_manual_schedule_rejected_by_gate(self):
        with pytest.raises(ValueError, match="manual-vjp"):
            supports_1f1b(CFG, _pcfg(pp=2), "wavefront")

    def test_cp_unsupported(self):
        pcfg = dict(_pcfg(pp=2), context_parallel_size=2)
        ok, reason = supports_1f1b(CFG, pcfg)
        assert not ok and "context" in reason
        assert resolve_schedule("auto", CFG, pcfg) == "wavefront"

    @pytest.mark.parametrize("alignment", ["dpo", "orpo", "kto"])
    def test_preference_alignment_unsupported(self, alignment):
        ok, reason = supports_1f1b(CFG, _pcfg(pp=2, alignment=alignment))
        assert not ok and alignment in reason

    def test_sft_alignment_supported(self):
        ok, _ = supports_1f1b(CFG, _pcfg(pp=2, alignment="sft"))
        assert ok

    def test_lora_unsupported(self):
        ok, reason = supports_1f1b(CFG, _pcfg(pp=2, lora=True))
        assert not ok and "LoRA" in reason

    def test_gpt_unsupported(self):
        from neuronx_distributed_training_tpu.models import gpt

        gc = gpt.GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                           num_attention_heads=4, max_position_embeddings=32)
        ok, reason = supports_1f1b(gc, _pcfg(pp=2))
        assert not ok and "GPTConfig" in reason

    def test_mixtral_unsupported_keeps_wavefront(self):
        """Dropless-MoE stage vjp has backend-dependent numerics inside the
        1f1b tick loop (bisected: loss exact, stage grads off by a few
        percent under the legacy fully-manual shard_map fallback), so the
        gate keeps mixtral on the autodiff wavefront — and ``auto`` must
        resolve there rather than erroring."""
        import dataclasses

        from neuronx_distributed_training_tpu.models import mixtral
        from neuronx_distributed_training_tpu.ops import moe as moe_ops

        xc = mixtral.MixtralConfig(
            llama=dataclasses.replace(CFG),
            moe=moe_ops.MoEConfig(num_experts=4, top_k=2, dropless=True),
        )
        ok, reason = supports_1f1b(xc, _pcfg(pp=2))
        assert not ok and "mixtral" in reason
        assert resolve_schedule("auto", xc, _pcfg(pp=2)) == "wavefront"
        with pytest.raises(ValueError, match="mixtral"):
            resolve_schedule("1f1b", xc, _pcfg(pp=2))

    def test_zigzag_unsupported(self):
        import dataclasses

        zz = dataclasses.replace(CFG, attention_impl="zigzag_ring")
        ok, reason = supports_1f1b(zz, _pcfg(pp=2))
        assert not ok and "zigzag" in reason


class TestResolveSchedule:
    def test_auto_picks_1f1b_when_supported(self):
        assert resolve_schedule("auto", CFG, _pcfg(pp=2)) == "1f1b"

    def test_auto_picks_interleaved_under_vp(self):
        assert resolve_schedule("auto", CFG, _pcfg(pp=2, vp=2)) \
            == "1f1b-interleaved"

    def test_auto_falls_back_to_wavefront(self):
        pcfg = dict(_pcfg(pp=2, vp=2), context_parallel_size=2)
        assert resolve_schedule("auto", CFG, pcfg) == "wavefront"

    def test_auto_never_picks_zb(self):
        """zb trades recompute for bubble — a per-plan call the autotune
        cost model prices; auto stays on the no-extra-compute default."""
        assert resolve_schedule("auto", CFG, _pcfg(pp=2)) == "1f1b"

    def test_forced_interleaved_and_zb(self):
        assert resolve_schedule("1f1b-interleaved", CFG, _pcfg(pp=2, vp=2)) \
            == "1f1b-interleaved"
        assert resolve_schedule("1f1b-zb", CFG, _pcfg(pp=2)) == "1f1b-zb"
        with pytest.raises(ValueError, match="nothing to interleave"):
            resolve_schedule("1f1b-interleaved", CFG, _pcfg(pp=2))
        with pytest.raises(ValueError, match="1f1b-interleaved"):
            resolve_schedule("1f1b-zb", CFG, _pcfg(pp=2, vp=2))

    def test_forced_wavefront_always_wins(self):
        assert resolve_schedule("wavefront", CFG, _pcfg(pp=2)) == "wavefront"

    def test_forced_1f1b_on_supported(self):
        assert resolve_schedule("1f1b", CFG, _pcfg(pp=2)) == "1f1b"

    def test_forced_1f1b_on_unsupported_raises_with_reason(self):
        with pytest.raises(ValueError, match="virtual"):
            resolve_schedule("1f1b", CFG, _pcfg(pp=2, vp=2))
        with pytest.raises(ValueError, match="dpo"):
            resolve_schedule("1f1b", CFG, _pcfg(pp=2, alignment="dpo"))

    def test_unknown_schedule_rejected(self):
        with pytest.raises(ValueError, match="pipeline.schedule"):
            resolve_schedule("gpipe", CFG, _pcfg(pp=2))
        assert PIPELINE_SCHEDULES == ("auto", "1f1b", "1f1b-interleaved",
                                      "1f1b-zb", "wavefront")
        assert MANUAL_VJP_SCHEDULES == ("1f1b", "1f1b-interleaved", "1f1b-zb")

    def test_default_none_means_auto(self):
        assert resolve_schedule(None, CFG, _pcfg(pp=2)) == "1f1b"


class TestParity:
    """1F1B loss and ALL grad families must match wavefront + jax.grad —
    the feature-defining test for a manual-vjp schedule."""

    @pytest.mark.parametrize("tied", [False, True])
    def test_pp2_loss_and_grads_match_wavefront(self, devices8, tied):
        import dataclasses

        cfg = dataclasses.replace(CFG, tie_word_embeddings=tied)
        params = llama.init_params(jax.random.PRNGKey(0), cfg, FP32)
        mbs = microbatches(jax.random.PRNGKey(1))
        hooks = llama.pipeline_hooks(cfg, FP32)
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
        sh_params, sh_mbs = shard_for(mesh, cfg, params, mbs)

        ref_l, ref_g = wavefront_loss_and_grad(mesh, hooks, sh_params, sh_mbs)
        loss, g = onef1b_loss_and_grad(mesh, cfg, hooks, sh_params, sh_mbs)

        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        assert_path_close(g["layers"], ref_g["layers"],
                          tuple(p[1:] for p in GRAD_PATHS), tag=f"(tied={tied})")
        np.testing.assert_allclose(
            np.asarray(g["head_params"]["final_norm"]["scale"]),
            np.asarray(ref_g["final_norm"]["scale"]), rtol=5e-4, atol=1e-5)
        d_embed = np.asarray(g["params_from_embed"]["embed"]["embedding"])
        if tied:
            # tied head: embed grad = embed-path cotangent + head matmul grad
            np.testing.assert_allclose(
                d_embed + np.asarray(g["head_weight"]),
                np.asarray(ref_g["embed"]["embedding"]), rtol=5e-4, atol=1e-5)
        else:
            np.testing.assert_allclose(
                d_embed, np.asarray(ref_g["embed"]["embedding"]),
                rtol=5e-4, atol=1e-5)
            np.testing.assert_allclose(
                np.asarray(g["head_weight"]).T,
                np.asarray(ref_g["lm_head"]["w"]), rtol=5e-4, atol=1e-5)

    def test_pp4_nm_not_divisible(self, devices8):
        """nm % pp != 0: padded embed-feed/cotangent slots must not leak."""
        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        mbs = microbatches(jax.random.PRNGKey(1), nm=6)  # pp=4 -> 2 pad rows
        hooks = llama.pipeline_hooks(CFG, FP32)
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=4))
        sh_params, sh_mbs = shard_for(mesh, CFG, params, mbs)

        ref_l, ref_g = wavefront_loss_and_grad(mesh, hooks, sh_params, sh_mbs)
        loss, g = onef1b_loss_and_grad(mesh, CFG, hooks, sh_params, sh_mbs)
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(g["params_from_embed"]["embed"]["embedding"]),
            np.asarray(ref_g["embed"]["embedding"]), rtol=5e-4, atol=1e-5,
            err_msg="(nm=6, pp=4)")
        assert_path_close(g["layers"], ref_g["layers"],
                          (("mlp", "down", "w"),), tag="(nm=6, pp=4)")

    def test_loss_mask_weighting(self, devices8):
        """Masked tokens drop out of loss AND denominator identically."""
        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        mbs = dict(microbatches(jax.random.PRNGKey(1)))
        mask = np.ones(np.asarray(mbs["input_ids"]).shape, np.float32)
        mask[0, :, :8] = 0.0
        mbs["loss_mask"] = jnp.asarray(mask)
        hooks = llama.pipeline_hooks(CFG, FP32)
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
        sh_params, sh_mbs = shard_for(mesh, CFG, params, mbs)

        ref_l, _ = wavefront_loss_and_grad(mesh, hooks, sh_params, sh_mbs)
        loss, _ = onef1b_loss_and_grad(mesh, CFG, hooks, sh_params, sh_mbs)
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)

    def test_return_contract(self, devices8):
        """The documented grads contract is a tested invariant: exactly the
        keys {layers, params_from_embed, head_params, head_weight}, with
        params_from_embed shaped like the FULL params tree (vjp applied
        internally — not a raw embed-feed cotangent)."""
        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        mbs = microbatches(jax.random.PRNGKey(1), nm=2)
        hooks = llama.pipeline_hooks(CFG, FP32)
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
        sh_params, sh_mbs = shard_for(mesh, CFG, params, mbs)
        _, g = onef1b_loss_and_grad(mesh, CFG, hooks, sh_params, sh_mbs)
        assert sorted(g) == ["head_params", "head_weight", "layers",
                             "params_from_embed"]
        assert (jax.tree_util.tree_structure(g["params_from_embed"])
                == jax.tree_util.tree_structure(params))
        same_shapes = jax.tree_util.tree_map(
            lambda a, b: a.shape == b.shape, g["params_from_embed"], params)
        assert all(jax.tree_util.tree_leaves(same_shapes))
        # head grads cover the head param subtree, vocab-major head weight
        assert sorted(g["head_params"]) == ["final_norm"]
        V, H = CFG.vocab_size, CFG.hidden_size
        assert g["head_weight"].shape == (V, H)

    def test_pp1_raises(self):
        hooks = llama.pipeline_hooks(CFG, FP32)
        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        mbs = microbatches(jax.random.PRNGKey(1), nm=2)
        embed_fn, stage_fn, _ = hooks
        hh, hp_of, hw_of, _fold = llama.onef1b_head_hooks(CFG, FP32)
        with pytest.raises(ValueError, match="pp > 1"):
            pipeline_loss_and_grad(
                params, params["layers"], mbs, embed_fn=embed_fn,
                stage_fn=stage_fn, head_hidden_fn=hh,
                head_params=hp_of(params), head_weight=hw_of(params),
                mesh=None)


class TestParityNewSchedules:
    """The circular interleaved 1F1B and the ZB-H1 split must hold the SAME
    parity bar as plain 1F1B: loss + all grad families vs wavefront +
    ``jax.grad`` at the pinned tolerances.  The wavefront reference runs with
    the identical vp (so both sides consume the identical interleaved layer
    layout and chunk schedule)."""

    def _compare(self, cfg, pp, vp, nm, *, zb=False, loss_mask=False,
                 tied=False):
        import dataclasses

        cfg = dataclasses.replace(cfg, tie_word_embeddings=tied)
        params = llama.init_params(jax.random.PRNGKey(0), cfg, FP32)
        mbs = dict(microbatches(jax.random.PRNGKey(1), nm=nm,
                                vocab=cfg.vocab_size))
        if loss_mask:
            mask = np.ones(np.asarray(mbs["input_ids"]).shape, np.float32)
            mask[0, :, :8] = 0.0
            mbs["loss_mask"] = jnp.asarray(mask)
        hooks = llama.pipeline_hooks(cfg, FP32)
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=pp,
                                     virtual_pipeline_model_parallel_size=vp))
        sh_params, sh_mbs = shard_for(mesh, cfg, params, mbs, vp=vp)

        ref_l, ref_g = wavefront_loss_and_grad(
            mesh, hooks, sh_params, sh_mbs, virtual_pipeline_size=vp)
        loss, g = onef1b_loss_and_grad(
            mesh, cfg, hooks, sh_params, sh_mbs,
            virtual_pipeline_size=vp, zero_bubble=zb)
        tag = f"(pp={pp}, vp={vp}, nm={nm}, zb={zb}, tied={tied})"
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5,
                                   err_msg=tag)
        assert_path_close(g["layers"], ref_g["layers"],
                          tuple(p[1:] for p in GRAD_PATHS), tag=tag)
        np.testing.assert_allclose(
            np.asarray(g["head_params"]["final_norm"]["scale"]),
            np.asarray(ref_g["final_norm"]["scale"]), rtol=5e-4, atol=1e-5,
            err_msg=tag)
        d_embed = np.asarray(g["params_from_embed"]["embed"]["embedding"])
        if tied:
            np.testing.assert_allclose(
                d_embed + np.asarray(g["head_weight"]),
                np.asarray(ref_g["embed"]["embedding"]), rtol=5e-4,
                atol=1e-5, err_msg=tag)
        else:
            np.testing.assert_allclose(
                d_embed, np.asarray(ref_g["embed"]["embedding"]),
                rtol=5e-4, atol=1e-5, err_msg=tag)
            np.testing.assert_allclose(
                np.asarray(g["head_weight"]).T,
                np.asarray(ref_g["lm_head"]["w"]), rtol=5e-4, atol=1e-5,
                err_msg=tag)

    @pytest.mark.parametrize("pp,nm,tied", [
        (2, 4, False), (2, 4, True), (2, 6, False), (4, 6, False),
    ])
    def test_interleaved_parity(self, devices8, pp, nm, tied):
        """vp=2 circular interleave at pp in {2, 4}, incl. nm % pp != 0 and
        tied embeddings.  pp=4 x vp=2 needs an 8-layer stack."""
        import dataclasses

        cfg = (dataclasses.replace(CFG, num_layers=8) if pp == 4 else CFG)
        self._compare(cfg, pp=pp, vp=2, nm=nm, tied=tied)

    @pytest.mark.parametrize("pp,nm,tied", [
        (2, 4, True), (2, 6, False), (4, 4, False), (4, 6, False),
    ])
    def test_zb_parity(self, devices8, pp, nm, tied):
        """ZB-H1 dgrad/wgrad split at pp in {2, 4}, incl. nm % pp != 0 and
        tied embeddings."""
        self._compare(CFG, pp=pp, vp=1, nm=nm, zb=True, tied=tied)

    def test_interleaved_loss_mask(self, devices8):
        self._compare(CFG, pp=2, vp=2, nm=4, loss_mask=True)

    def test_zb_loss_mask(self, devices8):
        self._compare(CFG, pp=2, vp=1, nm=4, zb=True, loss_mask=True)

    def test_zb_rejects_vp(self, devices8):
        hooks = llama.pipeline_hooks(CFG, FP32)
        embed_fn, stage_fn, _ = hooks
        hh, hp_of, hw_of, _fold = llama.onef1b_head_hooks(CFG, FP32)
        params = llama.init_params(jax.random.PRNGKey(0), CFG, FP32)
        mbs = microbatches(jax.random.PRNGKey(1), nm=4)
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2,
                                     virtual_pipeline_model_parallel_size=2))
        sh_params, sh_mbs = shard_for(mesh, CFG, params, mbs, vp=2)
        with pytest.raises(ValueError, match="vp == 1 only"):
            pipeline_loss_and_grad(
                sh_params, sh_params["layers"], sh_mbs, embed_fn=embed_fn,
                stage_fn=stage_fn, head_hidden_fn=hh,
                head_params=hp_of(sh_params), head_weight=hw_of(sh_params),
                mesh=mesh, virtual_pipeline_size=2, zero_bubble=True)

    def test_interleaved_needs_nm_ge_pp(self, devices8):
        """nm < pp would read the circular stores before their writes —
        must die loudly (same hazard rule the wavefront enforces)."""
        import dataclasses

        cfg = dataclasses.replace(CFG, num_layers=8)
        hooks = llama.pipeline_hooks(cfg, FP32)
        embed_fn, stage_fn, _ = hooks
        hh, hp_of, hw_of, _fold = llama.onef1b_head_hooks(cfg, FP32)
        params = llama.init_params(jax.random.PRNGKey(0), cfg, FP32)
        mbs = microbatches(jax.random.PRNGKey(1), nm=2)
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=4,
                                     virtual_pipeline_model_parallel_size=2))
        sh_params, sh_mbs = shard_for(mesh, cfg, params, mbs, vp=2)
        with pytest.raises(ValueError, match="num_microbatches >= pp"):
            pipeline_loss_and_grad(
                sh_params, sh_params["layers"], sh_mbs, embed_fn=embed_fn,
                stage_fn=stage_fn, head_hidden_fn=hh,
                head_params=hp_of(sh_params), head_weight=hw_of(sh_params),
                mesh=mesh, virtual_pipeline_size=2)


class TestBubbleModel:
    """The one bubble table telemetry, bench, and the autotune cost model
    share (``bubble_multiplier`` / ``predicted_bubble_fraction``)."""

    def test_classic_1f1b_and_wavefront(self):
        assert bubble_multiplier("1f1b", 4, 8) == pytest.approx(3 / 8)
        assert bubble_multiplier("wavefront", 4, 8) == pytest.approx(3 / 8)

    def test_wavefront_vp_divides(self):
        """The satellite fix: vp>1 wavefront utilization is
        nm*vp/(nm*vp + pp - 1), so the multiplier divides by nm*vp."""
        assert bubble_multiplier("wavefront", 4, 8, vp=2) \
            == pytest.approx(3 / 16)

    def test_interleaved_divides_by_nm_vp(self):
        assert bubble_multiplier("1f1b-interleaved", 4, 8, vp=2) \
            == pytest.approx(3 / 16)
        assert bubble_multiplier("1f1b-interleaved", 4, 8, vp=4) \
            == pytest.approx(3 / 32)

    def test_zb_is_the_warmup_third(self):
        assert bubble_multiplier("1f1b-zb", 4, 8) == pytest.approx(1 / 8)
        # strictly below plain 1f1b at every equal (pp, nm)
        for pp in (2, 4, 8):
            for nm in (4, 16, 64):
                assert bubble_multiplier("1f1b-zb", pp, nm) \
                    < bubble_multiplier("1f1b", pp, nm)

    def test_degenerate_cases(self):
        assert bubble_multiplier("1f1b", 1, 8) == 0.0
        assert bubble_multiplier(None, 4, 0) == 0.0
        assert predicted_bubble_fraction("none", 1, 8) == 0.0

    def test_fraction_is_of_total_step(self):
        b = bubble_multiplier("1f1b", 4, 8)
        assert predicted_bubble_fraction("1f1b", 4, 8) \
            == pytest.approx(b / (1 + b))
        # utilization identity: 1 - fraction == nm*vp/(nm*vp + pp - 1)
        assert 1 - predicted_bubble_fraction("wavefront", 4, 8, vp=2) \
            == pytest.approx(16 / 19)


class TestWorkTable:
    """The work-compacted schedule table (schedule as data): the executor's
    trip counts, orderings, and ring bounds are host-side facts that must
    hold by construction."""

    @pytest.mark.parametrize("sched,pp,nm,vp", [
        ("1f1b", 2, 4, 1), ("1f1b", 4, 8, 1), ("1f1b", 2, 16, 1),
        ("1f1b-interleaved", 2, 4, 2), ("1f1b-interleaved", 2, 16, 2),
        ("1f1b-interleaved", 4, 8, 2),
    ])
    def test_table_realizes_priced_bubble(self, sched, pp, nm, vp):
        """The compacted table's own bubble accounting equals the planner's
        closed-form b/(1+b) for 1f1b and the m-major interleave (nm % pp ==
        0): the executor realizes EXACTLY the priced asymptotics — the
        claim the old lockstep executor could not make."""
        b = bubble_multiplier(sched, pp, nm, vp)
        assert work_table(sched, pp, nm, vp).bubble_fraction() \
            == pytest.approx(b / (1 + b))
        assert predicted_bubble_fraction(sched, pp, nm, vp) \
            == pytest.approx(b / (1 + b))

    def test_compacted_span_below_lockstep(self):
        for sched, pp, nm, vp in [("1f1b", 2, 16, 1),
                                  ("1f1b-interleaved", 2, 16, 2),
                                  ("1f1b-zb", 2, 16, 1)]:
            t = work_table(sched, pp, nm, vp)
            assert t.span < t.lockstep_span, (sched, t.tick_counts())

    def test_dense_windows(self):
        """nm % pp == 0: the F and B windows are exactly nm*vp + pp - 1
        active ticks each — the compacted executor runs no more stage
        computations than the work demands plus the fill/drain triangles."""
        for sched, pp, nm, vp in [("1f1b", 2, 16, 1),
                                  ("1f1b-interleaved", 2, 16, 2)]:
            tc = work_table(sched, pp, nm, vp).tick_counts()
            assert tc["f_ticks"] == nm * vp + pp - 1
            assert tc["b_ticks"] == nm * vp + pp - 1
            assert tc["head_ticks"] == nm

    def test_interleave_ring_bound_beats_old_store(self):
        """The m-major interleave's interval-allocated rings are bounded by
        the schedule's true in-flight window — STRICTLY below the old
        lockstep store (vp*nm chunk inputs + two nm-slot hand-off rings)
        at the acceptance point pp=2/nm=16/vp=2, and independent of nm."""
        rings16 = ring_slot_counts("1f1b-interleaved", 2, 16, 2)
        assert rings16["total"] < 2 * 16  # old chunk-input store alone
        assert rings16["inflight"] < 2 * 16
        # nm-independence: the ring is a window, not a per-microbatch store
        rings32 = ring_slot_counts("1f1b-interleaved", 2, 32, 2)
        assert rings32["inflight"] == rings16["inflight"]
        assert rings32["total"] == rings16["total"]

    def test_zb_wgrad_fill_is_dense(self):
        """ZB's deferred wgrads land on rank-uniform fill ticks: every rank
        does a VALID wgrad on every wgrad tick (no masked wgrad burn)."""
        t = work_table("1f1b-zb", 4, 8, 1)
        w_valid = t.rank_cols["w_valid"]
        has_w = t.glob_cols["has_w"]
        assert int(has_w.sum()) == 8  # one dense tick per microbatch
        assert (w_valid[has_w].all(axis=1)).all()

    def test_slot_lifetimes_collision_free(self):
        """Re-derive every ring value's write->last-read lifetime from the
        table columns and assert no two values overlap in a slot."""
        for sched, pp, nm, vp in [("1f1b", 2, 6, 1),
                                  ("1f1b-interleaved", 4, 6, 2),
                                  ("1f1b-zb", 2, 6, 1)]:
            t = work_table(sched, pp, nm, vp)
            r, g = t.rank_cols, t.glob_cols
            for rank in range(pp):
                lives = {}  # slot -> list of (write, last_read)
                for tk in range(t.span):
                    if r["f_valid"][tk, rank]:
                        key = (int(r["f_c"][tk, rank]),
                               int(r["f_m"][tk, rank]))
                        lives.setdefault(int(r["f_slot"][tk, rank]),
                                         []).append([key, tk, tk])
                for tk in range(t.span):
                    for col, slot_col in (("b_valid", "b_slot"),
                                          ("w_valid", "w_x_slot")):
                        if col == "w_valid" and sched != "1f1b-zb":
                            continue
                        if r[col][tk, rank]:
                            slot = int(r[slot_col][tk, rank])
                            for rec in lives.get(slot, []):
                                kc, km = rec[0]
                                mm = int(r["b_m" if col == "b_valid"
                                           else "w_m"][tk, rank])
                                cc = int(r["b_c"][tk, rank]) \
                                    if col == "b_valid" else kc
                                if (kc, km) == (cc, mm):
                                    rec[2] = max(rec[2], tk)
                for slot, recs in lives.items():
                    recs.sort(key=lambda rec: rec[1])
                    for a, b in zip(recs, recs[1:]):
                        assert a[2] < b[1], (
                            f"{sched} rank {rank} slot {slot}: value "
                            f"{a[0]} (live to {a[2]}) collides with "
                            f"{b[0]} (written {b[1]})")
            assert g["has_f"].any() and g["has_b"].any()

    def test_rejects_non_manual_schedules(self):
        with pytest.raises(ValueError, match="manual-vjp"):
            work_table("wavefront", 2, 4)
        with pytest.raises(ValueError, match="inconsistent"):
            work_table("1f1b", 2, 4, vp=2)
        with pytest.raises(ValueError, match="pp > 1"):
            work_table("1f1b", 1, 4)


class TestMemoryBound:
    """The schedule's reason to exist, pinned via compiled memory analysis.

    Marginal temp bytes per extra microbatch: the wavefront retains ~2
    activation-sized residuals per microbatch (per-tick stage-input saves +
    the parked/head chain), the 1F1B only the embed feed + its cotangent
    (~1 activation per microbatch per rank) on top of its O(pp) in-flight
    ring.  Measured at nm ∈ {2, 8} on the pp=2 mesh."""

    def test_1f1b_temp_memory_sublinear_in_nm(self, devices8):
        import dataclasses

        from tests.conftest import lower_in_mesh

        cfg = dataclasses.replace(
            CFG, vocab_size=64, hidden_size=256, intermediate_size=256,
            num_attention_heads=2, num_kv_heads=2, max_position_embeddings=128,
        )
        mb, s = 8, 128
        act_bytes = mb * s * cfg.hidden_size * 4  # one fp32 microbatch act
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
        params = llama.init_params(jax.random.PRNGKey(0), cfg, FP32)
        embed_fn, stage_fn, loss_fn = llama.pipeline_hooks(cfg, FP32)
        hh, hp_of, hw_of, _fold = llama.onef1b_head_hooks(cfg, FP32)

        def wf(p, m):
            return pipeline_loss(p, p["layers"], m, embed_fn=embed_fn,
                                 stage_fn=stage_fn, loss_fn=loss_fn, mesh=mesh)

        def f1b(p, m):
            return pipeline_loss_and_grad(
                p, p["layers"], m, embed_fn=embed_fn, stage_fn=stage_fn,
                head_hidden_fn=hh, head_params=hp_of(p), head_weight=hw_of(p),
                mesh=mesh)

        temps = {}
        for nm in (2, 8):
            mbs = microbatches(jax.random.PRNGKey(1), nm=nm, mb=mb, s=s,
                               vocab=cfg.vocab_size)
            sh_params, sh_mbs = shard_for(mesh, cfg, params, mbs)
            temps[nm] = (
                lower_in_mesh(mesh, jax.value_and_grad(wf), sh_params, sh_mbs)
                .memory_analysis().temp_size_in_bytes,
                lower_in_mesh(mesh, f1b, sh_params, sh_mbs)
                .memory_analysis().temp_size_in_bytes,
            )
        wf_slope = (temps[8][0] - temps[2][0]) / 6.0
        f1b_slope = (temps[8][1] - temps[2][1]) / 6.0
        detail = {
            "temps": {k: tuple(int(x) for x in v) for k, v in temps.items()},
            "act_bytes": act_bytes,
            "wf_bytes_per_mb": wf_slope, "f1b_bytes_per_mb": f1b_slope,
        }
        # wavefront ~linear: >= 1.4 activation-sized residuals per microbatch
        assert wf_slope >= 1.4 * act_bytes, detail
        # 1F1B sub-linear: only the embed feed + cotangent scale with nm —
        # well under the wavefront's slope and ~1 activation per microbatch
        assert f1b_slope <= 0.75 * wf_slope, detail
        assert f1b_slope <= 1.25 * act_bytes, detail
        # and strictly less absolute temp memory once microbatches stack up
        assert temps[8][1] < temps[8][0], detail


    def test_schedule_memory_comparison(self, devices8):
        """The ISSUE's schedule-comparison bars on compiled peak temp bytes:
        zb stays within 1.15x plain 1F1B (its extra state is one pp-slot dy
        ring + the wgrad re-linearization workspace), and the interleave
        stays at-or-under the autodiff wavefront at the SAME vp (chunk-input
        rings vs ~2 per-layer residuals per work item)."""
        import dataclasses

        from tests.conftest import lower_in_mesh

        cfg = dataclasses.replace(
            CFG, vocab_size=64, hidden_size=256, intermediate_size=256,
            num_attention_heads=2, num_kv_heads=2, max_position_embeddings=128,
        )
        mb, s, nm = 8, 128, 8
        embed_fn, stage_fn, loss_fn = llama.pipeline_hooks(cfg, FP32)
        hh, hp_of, hw_of, _fold = llama.onef1b_head_hooks(cfg, FP32)
        params = llama.init_params(jax.random.PRNGKey(0), cfg, FP32)
        mbs = microbatches(jax.random.PRNGKey(1), nm=nm, mb=mb, s=s,
                           vocab=cfg.vocab_size)

        def peak(mesh, sh_params, sh_mbs, *, vp=1, zb=False, wavefront=False):
            if wavefront:
                def fn(p, m):
                    return pipeline_loss(
                        p, p["layers"], m, embed_fn=embed_fn,
                        stage_fn=stage_fn, loss_fn=loss_fn, mesh=mesh,
                        virtual_pipeline_size=vp)
                low = lower_in_mesh(mesh, jax.value_and_grad(fn),
                                    sh_params, sh_mbs)
            else:
                def fn(p, m):
                    return pipeline_loss_and_grad(
                        p, p["layers"], m, embed_fn=embed_fn,
                        stage_fn=stage_fn, head_hidden_fn=hh,
                        head_params=hp_of(p), head_weight=hw_of(p),
                        mesh=mesh, virtual_pipeline_size=vp, zero_bubble=zb)
                low = lower_in_mesh(mesh, fn, sh_params, sh_mbs)
            return low.memory_analysis().temp_size_in_bytes

        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2))
        sh_params, sh_mbs = shard_for(mesh, cfg, params, mbs)
        f1b = peak(mesh, sh_params, sh_mbs)
        zb = peak(mesh, sh_params, sh_mbs, zb=True)

        mesh_vp = build_mesh(MeshConfig(
            pipeline_model_parallel_size=2,
            virtual_pipeline_model_parallel_size=2))
        shp_vp, shm_vp = shard_for(mesh_vp, cfg, params, mbs, vp=2)
        il = peak(mesh_vp, shp_vp, shm_vp, vp=2)
        wf_vp = peak(mesh_vp, shp_vp, shm_vp, vp=2, wavefront=True)

        detail = {"f1b": f1b, "zb": zb, "interleaved": il,
                  "wavefront_vp": wf_vp}
        assert zb <= 1.15 * f1b, detail
        assert il <= wf_vp, detail

    def test_interleave_ring_memory_sublinear_in_nm(self, devices8):
        """The compacted executor's interval-allocated chunk-input ring is
        bounded by the schedule's in-flight window, not by nm: compiled
        temp bytes of the interleave grow by ~1 activation per extra
        microbatch (the embed feed + its cotangent — unavoidable), NOT the
        old lockstep store's ~(vp+2) activations per microbatch."""
        import dataclasses

        from tests.conftest import lower_in_mesh

        cfg = dataclasses.replace(
            CFG, vocab_size=64, hidden_size=256, intermediate_size=256,
            num_attention_heads=2, num_kv_heads=2, max_position_embeddings=128,
        )
        mb, s, vp = 8, 128, 2
        act_bytes = mb * s * cfg.hidden_size * 4
        embed_fn, stage_fn, _lf = llama.pipeline_hooks(cfg, FP32)
        hh, hp_of, hw_of, _fold = llama.onef1b_head_hooks(cfg, FP32)
        params = llama.init_params(jax.random.PRNGKey(0), cfg, FP32)
        mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2,
                                     virtual_pipeline_model_parallel_size=vp))

        temps = {}
        for nm in (8, 16):
            mbs = microbatches(jax.random.PRNGKey(1), nm=nm, mb=mb, s=s,
                               vocab=cfg.vocab_size)
            shp, shm = shard_for(mesh, cfg, params, mbs, vp=vp)

            def il(p, m):
                return pipeline_loss_and_grad(
                    p, p["layers"], m, embed_fn=embed_fn, stage_fn=stage_fn,
                    head_hidden_fn=hh, head_params=hp_of(p),
                    head_weight=hw_of(p), mesh=mesh, virtual_pipeline_size=vp)

            temps[nm] = lower_in_mesh(mesh, il, shp, shm) \
                .memory_analysis().temp_size_in_bytes
        slope = (temps[16] - temps[8]) / 8.0
        detail = {"temps": temps, "act_bytes": act_bytes,
                  "bytes_per_extra_mb": slope}
        # old lockstep store: (vp+2) = 4 stage inputs per extra microbatch
        # on top of the feed/cotangent pair; the ring bound drops that term
        assert slope <= 2.5 * act_bytes, detail


class TestTrainerDispatch:
    """The trainer builds the 1F1B loss+grad when the gate fires, feeding the
    identical AdamW/ZeRO-1 + metrics + grad-pinning path — one step under
    each schedule must produce the same loss AND grad_norm."""

    def _cfg(self, schedule, arch_overrides=None, vp=1):
        cfg = {
            "name": f"f1b_dispatch_{schedule}",
            "model_source": "hf",
            "seed": 0,
            "trainer": {"max_steps": 1, "log_every_n_steps": 1},
            "distributed_strategy": {
                "pipeline_model_parallel_size": 2,
                "virtual_pipeline_model_parallel_size": vp,
                "pipeline": {"schedule": schedule},
            },
            "data": {"global_batch_size": 8, "micro_batch_size": 1,
                     "seq_length": 16, "synthetic": True},
            "model": {
                "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
                "num_layers": 4, "num_attention_heads": 4,
                "num_key_value_heads": 2, "max_position_embeddings": 32,
                "activations_checkpoint_granularity": None,
                "optim": {"name": "adamw_fp32OptState", "lr": 1e-3,
                          "sched": {"name": "constant"}},
            },
            "precision": {"type": "fp32"},
        }
        if arch_overrides:
            cfg["model"].update(arch_overrides)
        return cfg

    def _one_step(self, schedule, vp=1):
        from neuronx_distributed_training_tpu.config.loader import load_config
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        t = Trainer.from_config(load_config(self._cfg(schedule, vp=vp)),
                                enable_checkpointing=False)
        batch = next(t.data_module.sharded_batches(t.mesh))
        with t.mesh, shd.use_mesh(t.mesh):
            _, _, metrics = t.train_step(t.params, t.opt_state, batch,
                                         jax.random.PRNGKey(0))
        return t.pipeline_schedule, {k: float(v) for k, v in metrics.items()}

    def test_schedules_produce_identical_step(self, devices8):
        sched_f, m_f = self._one_step("1f1b")
        sched_w, m_w = self._one_step("wavefront")
        assert sched_f == "1f1b" and sched_w == "wavefront"
        np.testing.assert_allclose(m_f["loss"], m_w["loss"], rtol=1e-5)
        np.testing.assert_allclose(m_f["grad_norm"], m_w["grad_norm"], rtol=1e-4)

    def test_zb_produces_identical_step(self, devices8):
        sched_z, m_z = self._one_step("1f1b-zb")
        sched_f, m_f = self._one_step("1f1b")
        assert sched_z == "1f1b-zb"
        np.testing.assert_allclose(m_z["loss"], m_f["loss"], rtol=1e-5)
        np.testing.assert_allclose(m_z["grad_norm"], m_f["grad_norm"],
                                   rtol=1e-4)

    def test_interleaved_produces_identical_step(self, devices8):
        sched_i, m_i = self._one_step("1f1b-interleaved", vp=2)
        sched_w, m_w = self._one_step("wavefront", vp=2)
        assert sched_i == "1f1b-interleaved" and sched_w == "wavefront"
        np.testing.assert_allclose(m_i["loss"], m_w["loss"], rtol=1e-5)
        np.testing.assert_allclose(m_i["grad_norm"], m_w["grad_norm"],
                                   rtol=1e-4)

    def test_auto_resolves_to_1f1b(self, devices8):
        from neuronx_distributed_training_tpu.config.loader import load_config
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        t = Trainer.from_config(load_config(self._cfg("auto")),
                                enable_checkpointing=False)
        assert t.pipeline_schedule == "1f1b"

    def test_auto_resolves_to_interleaved_under_vp(self, devices8):
        from neuronx_distributed_training_tpu.config.loader import load_config
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        t = Trainer.from_config(load_config(self._cfg("auto", vp=2)),
                                enable_checkpointing=False)
        assert t.pipeline_schedule == "1f1b-interleaved"
        # telemetry: the resolved schedule + the cost model's bubble
        # prediction ride run_facts into run_summary.json
        assert t.run_facts["pipeline_schedule"] == "1f1b-interleaved"
        nm = 2  # gbs=8, mbs=1, dp=4 (8 devices / pp=2)
        assert t.run_facts["bubble_fraction_predicted"] == pytest.approx(
            predicted_bubble_fraction("1f1b-interleaved", 2, nm, 2), abs=1e-6)
        # the compacted executor's per-step trip counts ride run_facts
        ticks = t.run_facts["pipeline_ticks_per_step"]
        assert ticks == work_table("1f1b-interleaved", 2, nm, 2).tick_counts()
        assert ticks["span"] < ticks["lockstep_span"]

    def test_forced_1f1b_on_gpt_raises(self, devices8):
        """The family gate fires at trainer build with the gate's reason —
        not deep inside shard_map."""
        from neuronx_distributed_training_tpu.config.loader import load_config
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = self._cfg("1f1b", arch_overrides={"architecture": "gpt"})
        with pytest.raises(ValueError, match="1f1b is unsupported"):
            Trainer.from_config(load_config(cfg), enable_checkpointing=False)
