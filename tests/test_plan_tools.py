"""tools/plan.py CLI + the shared tools/_jsonout.py writer.

The _jsonout contract under test is the satellite fix: with ``--json -`` the
LAST stdout line is exactly one parseable JSON document, even when logging
warnings are emitted mid-run (previously a stray log line could land after
the payload).
"""

import json
import logging
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")
TINY = os.path.join(REPO, "examples/conf/tiny_smoke_config.yaml")

sys.path.insert(0, TOOLS)


# ---------------------------------------------------------------------------
# _jsonout: the single-parseable-last-line contract
# ---------------------------------------------------------------------------


class TestJsonOut:
    def test_stdout_payload_is_single_last_line(self, capsys):
        from _jsonout import write_json

        # a logging handler writing to stdout — the failure mode the shared
        # writer exists to defeat (buffered log line landing after the JSON)
        logger = logging.getLogger("jsonout-test")
        handler = logging.StreamHandler(sys.stdout)
        logger.addHandler(handler)
        try:
            logger.warning("a stray warning before the payload")
            write_json({"ok": 1, "nested": {"a": [1, 2]}}, "-")
        finally:
            logger.removeHandler(handler)
        out = capsys.readouterr().out
        lines = [ln for ln in out.splitlines() if ln]
        assert json.loads(lines[-1]) == {"ok": 1, "nested": {"a": [1, 2]}}
        # the payload is ONE line (compact form), not a pretty-printed block
        assert lines[-1].startswith("{") and lines[-1].endswith("}")

    def test_file_payload_parses_whole_file(self, tmp_path):
        from _jsonout import write_json

        p = tmp_path / "out.json"
        write_json({"reports": [1, 2]}, str(p))
        assert json.loads(p.read_text()) == {"reports": [1, 2]}

    def test_flush_streams_is_safe_without_handlers(self):
        from _jsonout import flush_streams

        flush_streams()  # must never raise


def run_tool(args, timeout=240):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the tools size their own device world
    return subprocess.run(
        [sys.executable] + args, capture_output=True, text=True,
        timeout=timeout, cwd=REPO, env=env,
    )


# ---------------------------------------------------------------------------
# tools/plan.py
# ---------------------------------------------------------------------------


class TestPlanCLI:
    def test_check_tiny_smoke_passes_and_last_line_is_json(self):
        r = run_tool([os.path.join(TOOLS, "plan.py"), "--config", TINY,
                      "--check", "--json", "-"])
        assert r.returncode == 0, r.stdout + r.stderr
        lines = [ln for ln in r.stdout.splitlines() if ln]
        payload = json.loads(lines[-1])
        assert payload["check"][0]["ok"] is True
        assert payload["check"][0]["config"] == "tiny_smoke_config.yaml"

    def test_plan_with_audit_emits_report_and_applies(self, tmp_path):
        out_yaml = tmp_path / "tuned.yaml"
        out_json = tmp_path / "plan.json"
        r = run_tool([os.path.join(TOOLS, "plan.py"), "--config", TINY,
                      "--chips", "8", "--topology", "cpu", "--top-k", "2",
                      "--apply", str(out_yaml), "--json", str(out_json)])
        assert r.returncode == 0, r.stdout + r.stderr
        assert "winning knob block" in r.stdout
        payload = json.loads(out_json.read_text())
        rep = payload["reports"][0]
        assert rep["winner"] is not None
        # every non-discarded candidate passed the graph audit
        for c in rep["candidates"]:
            if "discarded" not in c:
                assert c["audit"]["verdict"] in ("clean", "info", "warn")
        # the applied copy loads and declares the winning mesh
        import yaml

        tuned = yaml.safe_load(out_yaml.read_text())
        assert (tuned["distributed_strategy"]["tensor_model_parallel_size"]
                == rep["winner"]["tp"])

    def test_nothing_to_do_errors(self):
        r = run_tool([os.path.join(TOOLS, "plan.py")], timeout=60)
        assert r.returncode != 0


# ---------------------------------------------------------------------------
# tools/preflight_audit.py rides the same writer
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestPreflightJsonLastLine:
    def test_last_stdout_line_is_json(self):
        r = run_tool([os.path.join(TOOLS, "preflight_audit.py"),
                      "--config", TINY, "--json", "-"])
        assert r.returncode == 0, r.stdout + r.stderr
        lines = [ln for ln in r.stdout.splitlines() if ln]
        payload = json.loads(lines[-1])
        assert payload["reports"][0]["config"] == "tiny_smoke_config.yaml"
