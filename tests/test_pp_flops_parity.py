"""Pipeline compiled-FLOPs parity regression gate (VERDICT r3 item 2).

The reference computes loss only on the last pipeline stage
(``base.py:378-381``).  This repo hoists the embed and the lm-head/CE out of
the SPMD wavefront (``parallel/pipeline.py``), so at equal tokens the
pipelined step's compiled FLOPs must stay within a few percent of the
unpipelined step — the residual is bubble-tick stage compute inherent to the
SPMD schedule.  Measured 1.0205x at pp=4 (bench_results/pp_flops_r4.md); this
test pins the property so a future pipeline change cannot silently regress to
the every-rank-every-tick head (which costs ``pp*(nm+pp-1)/nm``x head FLOPs,
4.75x at this shape).

Vocab >> hidden so the head term dominates, mirroring Llama-3's 128k vocab.
"""

import json

import jax
import pytest

from neuronx_distributed_training_tpu.config.loader import load_config
from neuronx_distributed_training_tpu.trainer.loop import Trainer

# the probe's exact shape (tools/pp_flops_probe.py, measured ratio 1.0205):
# the residual bubble term scales as (nm+pp-1)/nm on the stage fraction, so a
# smaller global batch (nm=8 instead of 16) reads ~1.15 — shape matters
HIDDEN = 128
LAYERS = 8
SEQ = 256
VOCAB = 8192
GBS = 32


def _cfg(pp: int) -> dict:
    return {
        "name": f"flopsgate_pp{pp}",
        "model_source": "hf",
        "seed": 0,
        "trainer": {"max_steps": 1, "log_every_n_steps": 1},
        "distributed_strategy": {
            "pipeline_model_parallel_size": pp,
            "tensor_model_parallel_size": 1,
        },
        "data": {"global_batch_size": GBS, "micro_batch_size": 1,
                 "seq_length": SEQ, "synthetic": True},
        "model": {
            "vocab_size": VOCAB,
            "hidden_size": HIDDEN,
            "intermediate_size": 2 * HIDDEN,
            "num_layers": LAYERS,
            "num_attention_heads": 4,
            "num_key_value_heads": 4,
            "max_position_embeddings": SEQ,
            "activations_checkpoint_granularity": "full",
            "optim": {"name": "adamw_fp32OptState", "lr": 1e-4,
                      "sched": {"name": "constant"}},
        },
        "precision": {"type": "fp32"},
    }


def _compiled_flops(pp: int) -> float:
    from tests.conftest import lower_in_mesh

    t = Trainer.from_config(load_config(_cfg(pp)), enable_checkpointing=False)
    batch = next(t.data_module.sharded_batches(t.mesh))
    # lower INSIDE the mesh context (shared guard helper): outside it every
    # shd.constrain in the step no-ops and the gate pins an unconstrained
    # graph — NOT the round-4 grad-sharding graph it exists to protect
    compiled = lower_in_mesh(
        t.mesh, t.train_step, t.params, t.opt_state, batch, jax.random.PRNGKey(0)
    )
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    return float(ca.get("flops", -1.0))


@pytest.mark.slow  # two full-train-step compiles on the 8-device mesh
def test_pp4_compiled_flops_within_10pct_of_unpipelined():
    f1 = _compiled_flops(1)
    f4 = _compiled_flops(4)
    assert f1 > 0 and f4 > 0, (f1, f4)
    ratio = f4 / f1
    # measured 1.0205 (pp_flops_r4.md); 1.10 leaves margin for XLA version
    # drift while still catching the 4.75x-head-class regression by a mile
    assert ratio < 1.10, json.dumps({"pp4_flops": f4, "pp1_flops": f1,
                                     "ratio": round(ratio, 4)})
