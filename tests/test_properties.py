"""Property-based invariants (hypothesis) for host-side data/layout logic.

These are the pure-Python seams where a shape or ordering bug silently
corrupts training data: the zig-zag CP permutation, the greedy sequence
packer (and its C++/numpy parity), fixed-length padding, SLURM nodelist
parsing, and the microbatch split.  Randomized inputs catch the edge cases
example-based tests hardcode around.
"""

import numpy as np
import pytest

# collect (and cleanly skip) on images without the hypothesis extra instead
# of erroring the whole tier-1 collection
pytest.importorskip(
    "hypothesis", reason="property tests need the hypothesis package")
from hypothesis import given, settings, strategies as st  # noqa: E402

import jax.numpy as jnp

from neuronx_distributed_training_tpu.data import packing
from neuronx_distributed_training_tpu.parallel.ring_attention import zigzag_positions
from neuronx_distributed_training_tpu.utils.launch import expand_first_host


@settings(max_examples=40, deadline=None)
@given(
    cp=st.integers(1, 8),
    half_chunk=st.integers(1, 16),
)
def test_zigzag_positions_is_permutation(cp, half_chunk):
    s = 2 * cp * half_chunk
    pos = np.asarray(zigzag_positions(s, cp))
    assert sorted(pos.tolist()) == list(range(s))
    # rank r holds chunks (r, 2cp-1-r): first half-chunk of rank 0 is the
    # lowest chunk, its second half-chunk the highest
    assert pos[0] == 0
    assert pos[half_chunk] == (2 * cp - 1) * half_chunk


@settings(max_examples=40, deadline=None)
@given(
    data=st.data(),
    chunk_size=st.integers(4, 64),
    eos_id=st.integers(0, 5),
)
def test_pack_sequences_preserves_tokens(data, chunk_size, eos_id):
    n = data.draw(st.integers(1, 12))
    seqs = [
        data.draw(st.lists(st.integers(6, 99), min_size=1, max_size=80))
        for _ in range(n)
    ]
    out = packing.pack_sequences(seqs, chunk_size, eos_id)
    ids = out["input_ids"]
    assert ids.ndim == 2 and (ids.shape[1] == chunk_size or ids.size == 0)
    # every kept record (len+eos <= chunk_size) appears, in order, with its
    # eos; oversize records are dropped (reference ConcatDataset rule)
    kept = [s for s in seqs if len(s) + 1 <= chunk_size]
    flat = ids.reshape(-1).tolist()
    want: list[int] = []
    for s in kept:
        want += list(s) + [eos_id]
    # remove padding: loss_mask marks real positions
    mask = out["loss_mask"].reshape(-1).astype(bool)
    assert [t for t, m in zip(flat, mask) if m] == want


@settings(max_examples=30, deadline=None)
@given(
    data=st.data(),
    max_length=st.integers(2, 32),
    left=st.booleans(),
)
def test_pad_sequences_shape_and_mask(data, max_length, left):
    n = data.draw(st.integers(1, 8))
    seqs = [
        data.draw(st.lists(st.integers(2, 50), min_size=1, max_size=40))
        for _ in range(n)
    ]
    out = packing.pad_sequences(seqs, max_length, pad_id=0, left_pad=left)
    assert out["input_ids"].shape == (n, max_length)
    for i, s in enumerate(seqs):
        keep = min(len(s), max_length)
        row = out["input_ids"][i]
        attn = out["attention_mask"][i]
        assert int(attn.sum()) == keep
        if left:
            assert row[max_length - keep:].tolist() == list(s)[:keep]
            assert (attn[: max_length - keep] == 0).all()
        else:
            assert row[:keep].tolist() == list(s)[:keep]
            assert (attn[keep:] == 0).all()


@settings(max_examples=50, deadline=None)
@given(
    prefix=st.from_regex(r"[a-z]{1,8}", fullmatch=True),
    start=st.integers(0, 99),
    end=st.integers(0, 99),
    pad=st.integers(1, 3),
)
def test_expand_first_host_slurm_ranges(prefix, start, end, pad):
    lo = min(start, end)
    hi = max(start, end)
    nodelist = f"{prefix}[{lo:0{pad}d}-{hi:0{pad}d}]"
    assert expand_first_host(nodelist) == f"{prefix}{lo:0{pad}d}"
    # plain comma list -> first entry
    assert expand_first_host(f"{prefix}7,{prefix}9") == f"{prefix}7"


@settings(max_examples=25, deadline=None)
@given(
    nm=st.integers(1, 8),
    per=st.integers(1, 4),
    s=st.integers(1, 8),
)
def test_microbatch_split_roundtrip(nm, per, s):
    from neuronx_distributed_training_tpu.trainer.step import microbatch_split

    batch = {"x": jnp.arange(nm * per * s).reshape(nm * per, s)}
    mbs = microbatch_split(batch, nm)
    assert mbs["x"].shape == (nm, per, s)
    np.testing.assert_array_equal(
        np.asarray(mbs["x"]).reshape(nm * per, s), np.asarray(batch["x"])
    )
