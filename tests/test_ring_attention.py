"""Ring attention vs core attention: numerics (fwd + grads) on a CP mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.ops.attention import core_attention
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
from neuronx_distributed_training_tpu.parallel.ring_attention import ring_attention

import pytest as _pytest_mark

pytestmark = _pytest_mark.mark.slow  # multi-minute parity tests; CI fast tier deselects


def make_qkv(key, b=2, s=64, h=4, kvh=None, d=16, dtype=jnp.float32):
    kvh = kvh or h
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), dtype)
    k = jax.random.normal(kk, (b, s, kvh, d), dtype)
    v = jax.random.normal(kv, (b, s, kvh, d), dtype)
    return q, k, v


@pytest.fixture(scope="module")
def cp_mesh():
    return build_mesh(MeshConfig(context_parallel_size=4))


@pytest.fixture(scope="module")
def cp_tp_mesh():
    return build_mesh(
        MeshConfig(context_parallel_size=2, tensor_model_parallel_size=2)
    )


class TestRingNumerics:
    def test_matches_core_causal(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(0))
        ref = core_attention(q, k, v, causal=True)
        with cp_mesh, shd.use_mesh(cp_mesh):
            out = jax.jit(lambda *a: ring_attention(*a, causal=True))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_matches_core_non_causal(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(1))
        ref = core_attention(q, k, v, causal=False)
        with cp_mesh, shd.use_mesh(cp_mesh):
            out = jax.jit(lambda *a: ring_attention(*a, causal=False))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_gqa(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(2), h=8, kvh=2)
        ref = core_attention(q, k, v, causal=True)
        with cp_mesh, shd.use_mesh(cp_mesh):
            out = jax.jit(lambda *a: ring_attention(*a))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_grads_match_core(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(3), s=32)

        def loss_ring(q, k, v):
            return jnp.sum(jnp.square(ring_attention(q, k, v, causal=True)))

        def loss_core(q, k, v):
            return jnp.sum(jnp.square(core_attention(q, k, v, causal=True)))

        ref_grads = jax.grad(loss_core, argnums=(0, 1, 2))(q, k, v)
        with cp_mesh, shd.use_mesh(cp_mesh):
            grads = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        for g, r in zip(grads, ref_grads):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=5e-4)

    def test_with_tp_and_cp(self, cp_tp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(4), h=4, kvh=2)
        ref = core_attention(q, k, v, causal=True)
        with cp_tp_mesh, shd.use_mesh(cp_tp_mesh):
            out = jax.jit(lambda *a: ring_attention(*a))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_sharded_inputs(self, cp_mesh):
        """Ring attention on inputs already sharded over context (the in-model
        situation under CP)."""
        q, k, v = make_qkv(jax.random.PRNGKey(5))
        spec = P(None, "context", None, None)
        sharding = NamedSharding(cp_mesh, spec)
        qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
        ref = core_attention(q, k, v, causal=True)
        with cp_mesh, shd.use_mesh(cp_mesh):
            out = jax.jit(lambda *a: ring_attention(*a))(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_cp1_fallback(self):
        q, k, v = make_qkv(jax.random.PRNGKey(6))
        out = ring_attention(q, k, v)  # no mesh active
        ref = core_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_kv_replication_tp_exceeds_kv_heads(self, devices8):
        """tp=4 > kv_heads=2: the kv_shared_group_size replication path
        (reference modeling_llama.py:310-320) — the 70B CP config shape class
        (tp=32, 8 kv heads).  Must run the actual ring, not a fallback."""
        mesh = build_mesh(
            MeshConfig(context_parallel_size=2, tensor_model_parallel_size=4)
        )
        q, k, v = make_qkv(jax.random.PRNGKey(8), h=8, kvh=2, s=32)
        ref = core_attention(q, k, v, causal=True)
        with mesh, shd.use_mesh(mesh):
            out = jax.jit(lambda *a: ring_attention(*a))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_kv_replication_grads(self, devices8):
        """Gradients flow correctly through the replicated KV heads (XLA sums
        the replica contributions back onto the original heads)."""
        mesh = build_mesh(
            MeshConfig(context_parallel_size=2, tensor_model_parallel_size=4)
        )
        q, k, v = make_qkv(jax.random.PRNGKey(9), h=8, kvh=2, s=32)

        def loss_ring(q, k, v):
            return jnp.sum(jnp.square(ring_attention(q, k, v, causal=True)))

        def loss_core(q, k, v):
            return jnp.sum(jnp.square(core_attention(q, k, v, causal=True)))

        ref_grads = jax.grad(loss_core, argnums=(0, 1, 2))(q, k, v)
        with mesh, shd.use_mesh(mesh):
            grads = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        for g, r in zip(grads, ref_grads):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=5e-4)

    def test_incompatible_heads_raise(self, devices8):
        """No silent fallback: head counts that divide neither way are an
        error, not quiet O(s^2) core attention."""
        mesh = build_mesh(
            MeshConfig(context_parallel_size=2, tensor_model_parallel_size=4)
        )
        q, k, v = make_qkv(jax.random.PRNGKey(10), h=8, kvh=3, s=32)
        with mesh, shd.use_mesh(mesh):
            with pytest.raises(ValueError, match="divide"):
                ring_attention(q, k, v)

    def test_sliding_window(self, cp_mesh):
        """Sliding-window masking with global ring offsets (the Mixtral
        use_sliding_window case ops.attention previously dropped)."""
        q, k, v = make_qkv(jax.random.PRNGKey(11), s=64)
        ref = core_attention(q, k, v, causal=True, sliding_window=16)
        with cp_mesh, shd.use_mesh(cp_mesh):
            out = jax.jit(
                lambda *a: ring_attention(*a, causal=True, sliding_window=16)
            )(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_blockwise_inner_matches(self, cp_mesh):
        """block_kv smaller than the chunk: the flash-style inner tiling must
        not change numerics."""
        q, k, v = make_qkv(jax.random.PRNGKey(12), s=128)
        ref = core_attention(q, k, v, causal=True)
        with cp_mesh, shd.use_mesh(cp_mesh):
            out = jax.jit(
                lambda *a: ring_attention(*a, causal=True, block_kv=8)
            )(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_ring_dispatch_rejects_q_offset(self, cp_mesh):
        from neuronx_distributed_training_tpu.ops.attention import attention

        q, k, v = make_qkv(jax.random.PRNGKey(13), s=32)
        with cp_mesh, shd.use_mesh(cp_mesh):
            with pytest.raises(ValueError, match="q_offset"):
                attention(q, k, v, impl="ring", q_offset=4)

    def test_ring_dispatch_passes_sliding_window(self, cp_mesh):
        from neuronx_distributed_training_tpu.ops.attention import attention

        q, k, v = make_qkv(jax.random.PRNGKey(14), s=64)
        ref = core_attention(q, k, v, causal=True, sliding_window=16)
        with cp_mesh, shd.use_mesh(cp_mesh):
            out = jax.jit(
                lambda *a: attention(*a, impl="ring", sliding_window=16)
            )(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_bf16(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(7), dtype=jnp.bfloat16)
        ref = core_attention(q, k, v, causal=True)
        with cp_mesh, shd.use_mesh(cp_mesh):
            out = jax.jit(lambda *a: ring_attention(*a))(q, k, v)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
        )


class TestFlashRing:
    """The Pallas-fused ring body (tileable shapes -> _ring_local_flash)."""

    @pytest.fixture(scope="class")
    def cp2_mesh(self):
        return build_mesh(MeshConfig(context_parallel_size=2))

    def _tileable_qkv(self, key, b=4, s=512, h=2, kvh=2, d=128):
        return make_qkv(key, b=b, s=s, h=h, kvh=kvh, d=d)

    def test_flash_path_selected_and_matches_core(self, cp2_mesh):
        from neuronx_distributed_training_tpu.ops.flash_attention import flash_tileable

        q, k, v = self._tileable_qkv(jax.random.PRNGKey(0))
        assert flash_tileable(256, 256, 128, 2, 2)  # s/cp local shapes tile
        ref = core_attention(q, k, v, causal=True)
        with cp2_mesh, shd.use_mesh(cp2_mesh):
            out = jax.jit(lambda *a: ring_attention(*a, causal=True))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_flash_ring_grads_match_core(self, cp2_mesh):
        q, k, v = self._tileable_qkv(jax.random.PRNGKey(1))

        def loss_ring(q, k, v):
            return jnp.sum(jnp.square(ring_attention(q, k, v, causal=True)))

        def loss_core(q, k, v):
            return jnp.sum(jnp.square(core_attention(q, k, v, causal=True)))

        ref_grads = jax.grad(loss_core, argnums=(0, 1, 2))(q, k, v)
        with cp2_mesh, shd.use_mesh(cp2_mesh):
            grads = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        for g, rg, name in zip(grads, ref_grads, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(rg), rtol=5e-4, atol=5e-4,
                err_msg=f"d{name} mismatch",
            )

    def test_flash_ring_gqa(self, cp2_mesh):
        q, k, v = self._tileable_qkv(jax.random.PRNGKey(2), h=4, kvh=2)
        ref = core_attention(q, k, v, causal=True)
        with cp2_mesh, shd.use_mesh(cp2_mesh):
            out = jax.jit(lambda *a: ring_attention(*a))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_flash_ring_sliding_window(self, cp2_mesh):
        q, k, v = self._tileable_qkv(jax.random.PRNGKey(3))
        ref = core_attention(q, k, v, causal=True, sliding_window=300)
        with cp2_mesh, shd.use_mesh(cp2_mesh):
            out = jax.jit(
                lambda *a: ring_attention(*a, causal=True, sliding_window=300)
            )(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_flash_ring_non_causal(self, cp2_mesh):
        q, k, v = self._tileable_qkv(jax.random.PRNGKey(4))
        ref = core_attention(q, k, v, causal=False)
        with cp2_mesh, shd.use_mesh(cp2_mesh):
            out = jax.jit(lambda *a: ring_attention(*a, causal=False))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_non_causal_window_is_ignored_like_core():
    """The window is causal-only across the stack (core_attention applies it
    inside the causal mask; flash drops it when causal=False): ring must
    match, not invent non-causal windowing the other impls don't have."""
    mesh = build_mesh(MeshConfig(context_parallel_size=2))
    q, k, v = make_qkv(jax.random.PRNGKey(21), b=4, s=512, h=2, kvh=2, d=128)
    ref = core_attention(q, k, v, causal=False, sliding_window=300)
    np.testing.assert_allclose(  # core itself ignores the window non-causally
        np.asarray(ref), np.asarray(core_attention(q, k, v, causal=False)),
        atol=1e-6)
    with mesh, shd.use_mesh(mesh):
        out = jax.jit(
            lambda *a: ring_attention(*a, causal=False, sliding_window=300)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_cp_attention_pipe_varying_grads(devices8):
    """Regression pin for the nested-shard_map backward hazard: CP attention
    invoked inside a Manual region (the pipeline body) with inputs that VARY
    over the manual axis must produce exact per-rank gradients.  The broken
    design (an inner shard_map under check_vma=False) kept the forward exact
    but summed cotangents across the outer axis — this test fails loudly if
    that path is ever reintroduced."""
    mesh = build_mesh(MeshConfig(pipeline_model_parallel_size=2,
                                 context_parallel_size=2))
    q = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 4, 16), jnp.float32)

    def per_rank_ref(q):
        total = 0.0
        for r in range(2):
            x = q * (r + 1)
            total += jnp.sum(jnp.square(core_attention(x, x, x, causal=True)))
        return total

    ref_g = jax.grad(per_rank_ref)(q)

    def piped(q):
        def body(q):
            r = jax.lax.axis_index("pipe").astype(q.dtype)
            x = q * (r + 1.0)  # pipe-VARYING input, like wavefront activations
            y = ring_attention(x, x, x, causal=True)
            return jax.lax.psum(jnp.sum(jnp.square(y)), "pipe")

        f = shd.shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(),
                          axis_names={"pipe"}, check_vma=False)
        return f(q)

    with mesh, shd.use_mesh(mesh):
        g = jax.jit(jax.grad(piped))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(ref_g), atol=5e-4)


class TestBlockwiseGspmd:
    """Direct unit gates for blockwise_gspmd_attention (the pp x cp body)."""

    def test_matches_core_causal(self):
        from neuronx_distributed_training_tpu.parallel.ring_attention import (
            blockwise_gspmd_attention,
        )

        q, k, v = make_qkv(jax.random.PRNGKey(0), s=96)  # non-divisible by 512
        ref = core_attention(q, k, v, causal=True)
        out = blockwise_gspmd_attention(q, k, v, causal=True, block_kv=32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_gqa_and_window(self):
        from neuronx_distributed_training_tpu.parallel.ring_attention import (
            blockwise_gspmd_attention,
        )

        q, k, v = make_qkv(jax.random.PRNGKey(1), h=8, kvh=2)
        ref = core_attention(q, k, v, causal=True, sliding_window=16)
        out = blockwise_gspmd_attention(
            q, k, v, causal=True, sliding_window=16, block_kv=16)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_odd_length_stays_blocked(self):
        """Non-dividing seq picks the largest divisor <= block_kv, never the
        O(s^2) single-block collapse."""
        from neuronx_distributed_training_tpu.parallel.ring_attention import (
            blockwise_gspmd_attention,
        )

        q, k, v = make_qkv(jax.random.PRNGKey(2), s=60)  # 60 % 32 != 0
        ref = core_attention(q, k, v, causal=True)
        out = blockwise_gspmd_attention(q, k, v, causal=True, block_kv=32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_grads_match_core(self):
        from neuronx_distributed_training_tpu.parallel.ring_attention import (
            blockwise_gspmd_attention,
        )

        q, k, v = make_qkv(jax.random.PRNGKey(3), s=64)

        def loss_b(q, k, v):
            return jnp.sum(jnp.square(
                blockwise_gspmd_attention(q, k, v, causal=True, block_kv=16)))

        def loss_c(q, k, v):
            return jnp.sum(jnp.square(core_attention(q, k, v, causal=True)))

        ref_g = jax.grad(loss_c, argnums=(0, 1, 2))(q, k, v)
        g = jax.jit(jax.grad(loss_b, argnums=(0, 1, 2)))(q, k, v)
        for a, r in zip(g, ref_g):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), atol=5e-4)


class TestRingMasked:
    """attention_mask (padded batches) stays on the ring path (VERDICT r2)."""

    def _mask(self, b, s, valid):
        from tests.conftest import ragged_right_pad_mask

        return ragged_right_pad_mask(b, s, valid)

    def _ref(self, q, k, v, mask, causal=True):
        from neuronx_distributed_training_tpu.ops.attention import (
            padding_mask_bias,
        )

        return core_attention(q, k, v, causal=causal,
                              bias=padding_mask_bias(mask))

    def test_masked_matches_core(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(40))
        mask = self._mask(2, 64, [50, 33])
        ref = self._ref(q, k, v, mask)
        with cp_mesh, shd.use_mesh(cp_mesh):
            out = jax.jit(lambda *a: ring_attention(
                *a[:3], causal=True, attention_mask=a[3]))(q, k, v, mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_masked_grads_match_core(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(41))
        mask = self._mask(2, 64, [48, 21])

        def loss_ring(q, k, v):
            o = ring_attention(q, k, v, causal=True, attention_mask=mask)
            return jnp.sum(o * o)

        def loss_core(q, k, v):
            return jnp.sum(self._ref(q, k, v, mask) ** 2)

        with cp_mesh, shd.use_mesh(cp_mesh):
            gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        gc = jax.grad(loss_core, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gr, gc, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-5,
                err_msg=f"d{name} mismatch under mask",
            )

    def test_masked_flash_ring_path(self, cp2_mesh=None):
        # lane-aligned shapes so the flash-fused ring body runs with the mask
        mesh = build_mesh(MeshConfig(context_parallel_size=2))
        q, k, v = make_qkv(jax.random.PRNGKey(42), b=4, s=512, h=2, d=128)
        mask = self._mask(4, 512, [300, 512, 129, 77])
        ref = self._ref(q, k, v, mask)
        from neuronx_distributed_training_tpu.ops.flash_attention import (
            flash_tileable,
        )

        assert flash_tileable(256, 256, 128, 2, 2)  # flash body is active
        with mesh, shd.use_mesh(mesh):
            out = jax.jit(lambda *a: ring_attention(
                *a[:3], causal=True, attention_mask=a[3]))(q, k, v, mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3, rtol=2e-3)

    def test_masked_blockwise_gspmd(self):
        from neuronx_distributed_training_tpu.parallel.ring_attention import (
            blockwise_gspmd_attention,
        )

        q, k, v = make_qkv(jax.random.PRNGKey(43))
        mask = self._mask(2, 64, [40, 64])
        ref = self._ref(q, k, v, mask)
        out = blockwise_gspmd_attention(q, k, v, causal=True, block_kv=16,
                                        attention_mask=mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
