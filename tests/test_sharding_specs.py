"""PartitionSpec resolution edge cases: the static spec lint
(``sharding.spec_errors``/``validate_specs``) plus the ``act_spec``/
``heads_spec``/``logits_spec`` composition rules under SP/CP combinations —
the spec-level contracts the graph auditor (GA401) builds on."""

import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from neuronx_distributed_training_tpu.parallel.mesh import (
    MeshConfig,
    build_mesh,
)
from neuronx_distributed_training_tpu.parallel.sharding import (
    act_spec,
    heads_spec,
    logits_spec,
    seq_axes,
    spec_errors,
    validate_specs,
)


@pytest.fixture(scope="module")
def mesh(devices8):
    # pipe=1 data=2 expert=1 context=2 model=2
    return build_mesh(
        MeshConfig(tensor_model_parallel_size=2, context_parallel_size=2),
        devices=devices8,
    )


class TestSpecErrors:
    def test_clean_specs(self, mesh):
        specs = {
            "w": P(None, "model"),
            "embed": P("model", None),
            "act": P(("data", "expert"), "context", None),
            "free": None,
            "replicated": P(),
        }
        assert spec_errors(specs, mesh) == []

    def test_absent_axis(self, mesh):
        errs = spec_errors({"w": P("tensor")}, mesh)
        assert len(errs) == 1
        assert "tensor" in errs[0] and "absent" in errs[0]
        assert "w" in errs[0]  # the leaf path is named

    def test_conflicting_axis_across_dims(self, mesh):
        """One mesh axis naming two tensor dims of the same spec."""
        errs = spec_errors({"w": P("model", "model")}, mesh)
        assert len(errs) == 1 and "twice" in errs[0]

    def test_conflict_inside_compound_axis(self, mesh):
        """Duplicate via a compound dim: P(('data','expert'), 'data')."""
        errs = spec_errors({"x": P(("data", "expert"), "data")}, mesh)
        assert len(errs) == 1 and "'data'" in errs[0]

    def test_multiple_defects_all_reported(self, mesh):
        errs = spec_errors(
            {"a": P("bogus"), "b": P("model", "model")}, mesh)
        assert len(errs) == 2

    def test_validate_specs_raises_curated(self, mesh):
        with pytest.raises(ValueError, match="invalid PartitionSpecs"):
            validate_specs({"w": P("bogus_axis")}, mesh)

    def test_nested_tree_paths(self, mesh):
        errs = spec_errors(
            {"layers": {"attn": {"q": P("nope")}}}, mesh)
        assert "layers/attn/q" in errs[0]


class TestSeqAxisComposition:
    """CP splits the sequence first (outer), Megatron-SP shards the
    remainder over the TP group — and the composed specs must stay legal
    (each axis used at most once)."""

    def test_seq_axes_combinations(self):
        assert seq_axes(False, False) is None
        assert seq_axes(True, False) == "model"
        assert seq_axes(False, True) == "context"
        assert seq_axes(True, True) == ("context", "model")

    def test_act_spec_sp_under_cp_is_legal(self, mesh):
        """sequence-parallel spec under cp>1: the compound seq dim uses
        context AND model — exactly once each."""
        spec = act_spec(sequence_parallel=True, context_parallel=True)
        assert spec == P(("data", "expert"), ("context", "model"), None)
        assert spec_errors({"act": spec}, mesh) == []

    def test_heads_spec_under_cp(self, mesh):
        """attention-internal: heads take model, seq keeps ONLY context
        (attention needs the full TP-group sequence) — using model on both
        would be the double-use defect spec_errors exists to catch."""
        spec = heads_spec(context_parallel=True)
        assert spec == P(("data", "expert"), "context", "model", None)
        assert spec_errors({"heads": spec}, mesh) == []

    def test_logits_spec_vocab_over_model(self, mesh):
        spec = logits_spec(context_parallel=True)
        assert spec == P(("data", "expert"), "context", "model")
        assert spec_errors({"logits": spec}, mesh) == []

    def test_sp_act_spec_on_cp_free_mesh(self, devices8):
        """The same SP+CP spec against a mesh WITHOUT a context axis must be
        flagged, not silently ignored."""
        flat = Mesh(np.asarray(devices8).reshape(4, 2), ("data", "model"))
        spec = act_spec(sequence_parallel=True, context_parallel=True)
        errs = spec_errors({"act": spec}, flat)
        assert len(errs) >= 1 and "context" in errs[0]
        # 'expert' from the compound batch axis is missing on this mesh too
        assert any("expert" in e for e in errs)
