"""Unified step telemetry (telemetry/ + trainer wiring): span decomposition,
MFU plumbing per model family, compile census / run_summary.json schema,
recompile detection, goodput accounting, and the dispatch-ahead contract
(zero host syncs between logging boundaries) — all tier-1 / CPU."""

import importlib.util
import json
import logging
import time
from pathlib import Path

import numpy as np
import pytest

from neuronx_distributed_training_tpu.telemetry import (
    RecompileDetector,
    SpanTimer,
    TelemetryConfig,
)
from neuronx_distributed_training_tpu.utils import perf


# ---------------------------------------------------------------------------
# spans + goodput
# ---------------------------------------------------------------------------


class TestSpanTimer:
    def test_span_decomposition_sums_to_wall(self):
        spans = SpanTimer()
        t0 = time.perf_counter()
        with spans.span("data_wait"):
            time.sleep(0.02)
        with spans.span("dispatch"):
            time.sleep(0.01)
        with spans.span("host_sync"):
            time.sleep(0.01)
        wall = time.perf_counter() - t0
        got = spans.drain()
        assert set(got) == {"data_wait", "dispatch", "host_sync"}
        total = sum(got.values())
        # the spans cover everything but loop overhead: they must sum to
        # within a few ms of the elapsed wall time, and never exceed it
        assert total <= wall + 1e-6
        assert total >= wall - 0.02, (total, wall)
        assert got["data_wait"] >= 0.015

    def test_drain_resets_but_goodput_accumulates(self):
        spans = SpanTimer()
        spans.add("checkpoint", 2.0)
        assert spans.drain() == {"checkpoint": 2.0}
        assert spans.drain() == {}
        spans.add("checkpoint", 1.0)
        assert spans.nonproductive_seconds() == pytest.approx(3.0)

    def test_take_excluded_covers_nonproductive_only(self):
        spans = SpanTimer()
        spans.add("dispatch", 5.0)
        spans.add("validate", 1.5)
        spans.add("compile", 2.0)
        assert spans.take_excluded() == pytest.approx(3.5)
        assert spans.take_excluded() == 0.0  # reset on take
        spans.add("checkpoint", 0.5)
        assert spans.take_excluded() == pytest.approx(0.5)

    def test_goodput_fraction_and_summary(self):
        spans = SpanTimer()
        spans.add("checkpoint", 1.0)
        wall = spans.wall_seconds
        frac = spans.goodput_fraction()
        assert 0.0 <= frac <= 1.0
        s = spans.goodput_summary()
        assert s["nonproductive_seconds"] == pytest.approx(1.0)
        assert s["breakdown_seconds"] == {"checkpoint": 1.0}
        # productive is derived, clamped at zero (here the synthetic 1.0 s of
        # checkpoint exceeds the real ~0 s wall)
        assert s["productive_seconds"] == pytest.approx(
            max(s["wall_seconds"] - s["nonproductive_seconds"], 0.0), abs=1e-6)
        assert wall >= 0.0

    def test_disabled_timer_is_inert(self):
        spans = SpanTimer(enabled=False)
        with spans.span("validate"):
            pass
        spans.add("checkpoint", 9.0)
        assert spans.drain() == {}
        assert spans.take_excluded() == 0.0
        assert spans.goodput_fraction() == pytest.approx(1.0, abs=1e-3)


# ---------------------------------------------------------------------------
# recompile / retrace detection
# ---------------------------------------------------------------------------


class TestRecompileDetector:
    def test_fires_on_forced_shape_change_with_diff(self, caplog):
        det = RecompileDetector()
        b1 = {"input_ids": np.zeros((8, 32), np.int32)}
        b2 = {"input_ids": np.zeros((5, 32), np.int32)}  # ragged final batch
        assert det.check("train_step", b1) is False
        assert det.check("train_step", b1) is False  # stable: no event
        with caplog.at_level(
                logging.WARNING,
                logger="neuronx_distributed_training_tpu.telemetry.recompile"):
            assert det.check("train_step", b2) is True
        assert det.events and "train_step" in det.events[0]
        msg = caplog.records[-1].message
        assert "8,32" in msg and "5,32" in msg, msg

    def test_structure_change_reports_added_leaf(self):
        det = RecompileDetector()
        det.check("f", {"a": np.zeros((2,), np.float32)})
        assert det.check("f", {"a": np.zeros((2,), np.float32),
                               "b": np.zeros((3,), np.float32)}) is True
        assert "added" in det.events[-1]

    def test_independent_names(self):
        det = RecompileDetector()
        det.check("train", {"x": np.zeros((4,), np.float32)})
        # a different fn with different shapes is NOT a retrace of the first
        assert det.check("eval", {"x": np.zeros((2,), np.float32)}) is False


# ---------------------------------------------------------------------------
# Throughput warm-up + tokens_per_sec (one source of truth for MFU)
# ---------------------------------------------------------------------------


class TestThroughput:
    def test_peak_waits_for_min_samples(self):
        t = perf.Throughput(batch_size=8, window=10)
        # a one-off fast first window must not pin a phantom peak
        t.update(0.001)
        assert t.peak == 0.0
        t.update(1.0)
        assert t.peak == 0.0
        t.update(1.0)  # 3rd sample: window is representative now
        assert t.peak > 0.0

    def test_small_window_records_immediately(self):
        t = perf.Throughput(batch_size=8, window=1)
        t.update(1.0)
        assert t.peak == pytest.approx(8.0)

    def test_tokens_per_sec_derives_from_seq_len(self):
        t = perf.Throughput(batch_size=4, window=10, seq_len=32)
        assert t.tokens_per_sec == 0.0
        rate = t.update(2.0)  # 4 seqs / 2 s = 2 seq/s
        assert rate == pytest.approx(2.0)
        assert t.last == pytest.approx(2.0)
        assert t.tokens_per_sec == pytest.approx(2.0 * 32)


# ---------------------------------------------------------------------------
# per-family analytic FLOPs (the MFU numerator)
# ---------------------------------------------------------------------------


class TestFlopsForModel:
    def _llama(self, **kw):
        from neuronx_distributed_training_tpu.models import llama

        base = dict(vocab_size=1024, hidden_size=64, intermediate_size=128,
                    num_layers=4, num_attention_heads=4, num_kv_heads=2,
                    max_position_embeddings=64)
        base.update(kw)
        return llama.LlamaConfig(**base)

    def test_llama_matches_flops_for_config(self):
        cfg = self._llama()
        assert perf.flops_for_model(cfg, 64) == perf.flops_for_config(cfg, 64)
        assert perf.flops_for_model(cfg, 64) > 0

    def test_mixtral_counts_activated_experts_only(self):
        from neuronx_distributed_training_tpu.models import mixtral
        from neuronx_distributed_training_tpu.ops.moe import MoEConfig

        mk = lambda k: mixtral.MixtralConfig(
            llama=self._llama(), moe=MoEConfig(num_experts=8, top_k=k))
        f1, f2 = perf.flops_for_model(mk(1), 64), perf.flops_for_model(mk(2), 64)
        assert f2 > f1 > 0
        # top_k=2 adds exactly one more expert's SwiGLU per MoE layer
        swiglu = 2 * 64 * 3 * 128
        assert f2 - f1 == pytest.approx(4 * swiglu)
        # dense llama vs top_k=1 mixtral differ only by the router matmul
        dense = perf.flops_for_model(self._llama(), 64)
        router = 2 * 64 * 8
        assert f1 - dense == pytest.approx(4 * router)

    def test_gpt_glu_vs_plain_activation(self):
        from neuronx_distributed_training_tpu.models import gpt

        mk = lambda act: gpt.GPTConfig(
            vocab_size=1024, hidden_size=64, ffn_hidden_size=128,
            num_layers=4, num_attention_heads=4, activation=act)
        plain, glu = (perf.flops_for_model(mk("gelu"), 64),
                      perf.flops_for_model(mk("swiglu"), 64))
        # GLU runs 3 MLP matmuls to plain's 2 at equal ffn width
        mlp2 = 4 * 2 * 64 * 2 * 128
        assert glu - plain == pytest.approx(mlp2 / 2)
        assert plain > 0

    def test_gpt_moe(self):
        from neuronx_distributed_training_tpu.models import gpt
        from neuronx_distributed_training_tpu.ops.moe import MoEConfig

        dense = gpt.GPTConfig(vocab_size=1024, hidden_size=64,
                              num_layers=4, num_attention_heads=4)
        moe = gpt.GPTConfig(vocab_size=1024, hidden_size=64,
                            num_layers=4, num_attention_heads=4,
                            moe=MoEConfig(num_experts=4, top_k=2))
        assert perf.flops_for_model(moe, 64) > perf.flops_for_model(dense, 64)


# ---------------------------------------------------------------------------
# exp_manager.telemetry config validation / round-trip
# ---------------------------------------------------------------------------


class TestTelemetryConfig:
    def test_defaults(self):
        tc = TelemetryConfig.from_config(None)
        assert tc.spans and tc.mfu and tc.compile_census and tc.goodput
        assert not tc.device_memory  # the one backend-query knob is opt-in

    def test_unknown_key_rejected_at_load(self):
        from neuronx_distributed_training_tpu.config.loader import load_config

        cfg = {"exp_manager": {"telemetry": {"spanz": True}},
               "data": {"global_batch_size": 8, "micro_batch_size": 1}}
        with pytest.raises(ValueError, match="spanz"):
            load_config(cfg)

    def test_non_bool_rejected(self):
        with pytest.raises(ValueError, match="boolean"):
            TelemetryConfig.from_config({"mfu": "yes"})

    def test_blanket_off(self):
        tc = TelemetryConfig.from_config(False)
        assert not (tc.spans or tc.mfu or tc.compile_census or tc.goodput
                    or tc.device_memory)

    def test_round_trip_through_exp_manager(self, tmp_path):
        from neuronx_distributed_training_tpu.config.loader import load_config
        from neuronx_distributed_training_tpu.trainer.exp_manager import ExpManager

        cfg = load_config({
            "exp_manager": {"exp_dir": str(tmp_path), "log_files": False,
                            "create_tensorboard_logger": False,
                            "telemetry": {"device_memory": True,
                                          "goodput": False}},
            "data": {"global_batch_size": 8, "micro_batch_size": 1,
                     "seq_length": 64},
        })
        exp = ExpManager.from_config(cfg, global_batch_size=8)
        assert exp.telemetry.device_memory is True
        assert exp.telemetry.goodput is False
        assert exp.telemetry.spans is True  # unmentioned knob keeps default
        assert exp.throughput.seq_len == 64
        exp.close()


# ---------------------------------------------------------------------------
# step_timed decontamination + MFU logging (ExpManager level)
# ---------------------------------------------------------------------------


class TestExpManagerTelemetry:
    def _exp(self, tmp_path, **kw):
        from neuronx_distributed_training_tpu.trainer.exp_manager import ExpManager

        return ExpManager(exp_dir=str(tmp_path), log_files=False,
                          create_tensorboard_logger=False, **kw)

    def test_step_timed_excludes_nonproductive_wall(self, tmp_path, monkeypatch):
        from neuronx_distributed_training_tpu.trainer import exp_manager as em

        clock = {"t": 100.0}
        monkeypatch.setattr(em.time, "perf_counter", lambda: clock["t"])
        exp = self._exp(tmp_path, global_batch_size=8)
        exp.step_timed()  # arm
        clock["t"] = 110.0
        # 10 s window over 2 steps, 6 s of it checkpoint/validate stall:
        # per-step time must be (10 - 6) / 2, not 5
        dt = exp.step_timed(2, exclude_seconds=6.0)
        assert dt == pytest.approx(2.0)
        assert exp.throughput.last == pytest.approx(8.0 / 2.0)
        exp.close()

    def test_mfu_logged_from_single_source_of_truth(self, tmp_path):
        exp = self._exp(tmp_path, global_batch_size=4, seq_len=128,
                        log_every_n_steps=1)
        exp.set_mfu_reference(train_step_flops_per_token=6e6, n_chips=2,
                              peak_tflops_per_chip=0.5)
        exp.step_timed()
        time.sleep(0.01)
        exp.step_timed(1)
        exp.log_metrics(1, {"loss": 1.0})
        exp.close()
        rec = json.loads(
            (exp.log_dir / "metrics.jsonl").read_text().strip().splitlines()[-1])
        assert rec["tokens_per_sec_per_chip"] == pytest.approx(
            exp.throughput.tokens_per_sec / 2)
        assert rec["mfu"] == pytest.approx(
            rec["tokens_per_sec_per_chip"] * 6e6 / 0.5e12)

    def test_run_summary_merges_sections(self, tmp_path):
        exp = self._exp(tmp_path)
        exp.write_run_summary({"compile_seconds": 1.5})
        exp.write_run_summary({"goodput": {"goodput_fraction": 0.9}})
        got = json.loads((exp.log_dir / "run_summary.json").read_text())
        assert got["compile_seconds"] == 1.5
        assert got["goodput"]["goodput_fraction"] == 0.9
        exp.close()


# ---------------------------------------------------------------------------
# trainer integration: the CPU smoke run of the acceptance criteria
# ---------------------------------------------------------------------------


def _tiny_cfg(tmp_path, **over):
    from neuronx_distributed_training_tpu.config.loader import load_config

    cfg = {
        "name": "tel", "model_source": "hf", "seed": 7,
        "trainer": {"max_steps": 3, "log_every_n_steps": 1,
                    "val_check_interval": 3, "limit_val_batches": 1},
        "exp_manager": {"exp_dir": str(tmp_path / "exp"),
                        "create_tensorboard_logger": False,
                        "log_files": False},
        "distributed_strategy": {"tensor_model_parallel_size": 2,
                                 "sequence_parallel": True},
        "data": {"global_batch_size": 8, "micro_batch_size": 1,
                 "seq_length": 32, "synthetic": True},
        "model": {"vocab_size": 128, "hidden_size": 64,
                  "intermediate_size": 128, "num_layers": 2,
                  "num_attention_heads": 4, "num_key_value_heads": 2,
                  "max_position_embeddings": 32,
                  "optim": {"name": "adamw_fp32OptState", "lr": 1e-3}},
        "precision": {"type": "mixed_precision"},
    }
    cfg.update(over)
    return load_config(cfg)


@pytest.fixture(scope="module")
def telemetry_run(tmp_path_factory, devices8):
    """One tiny fit() with full telemetry; shared across schema assertions."""
    from neuronx_distributed_training_tpu.data import SyntheticDataModule
    from neuronx_distributed_training_tpu.trainer.loop import Trainer

    tmp_path = tmp_path_factory.mktemp("telemetry_run")
    cfg = _tiny_cfg(tmp_path)
    val = SyntheticDataModule(vocab_size=128, seq_len=32,
                              global_batch_size=8, seed=9)
    t = Trainer.from_config(cfg, val_data_module=val,
                            enable_checkpointing=False)
    metrics = t.fit()
    exp_dir = tmp_path / "exp" / "tel" / "version_0"
    records = [json.loads(l) for l in
               (exp_dir / "metrics.jsonl").read_text().strip().splitlines()]
    summary = json.loads((exp_dir / "run_summary.json").read_text())
    return t, metrics, records, summary


class TestTrainerTelemetry:
    def test_metrics_jsonl_schema(self, telemetry_run):
        _, metrics, records, _ = telemetry_run
        boundary = [r for r in records if "step_time" in r]
        assert boundary, records
        last = boundary[-1]
        for key in ("mfu", "tokens_per_sec_per_chip", "goodput_fraction",
                    "time/data_wait", "time/dispatch", "time/host_sync",
                    "throughput_seqs_per_sec", "loss", "lr"):
            assert key in last, (key, sorted(last))
        assert 0.0 <= last["goodput_fraction"] <= 1.0
        assert last["mfu"] > 0.0
        assert np.isfinite(metrics["val_loss"])

    def test_first_boundary_carries_compile_span(self, telemetry_run):
        _, _, records, _ = telemetry_run
        first = next(r for r in records if "step_time" in r)
        assert first.get("time/compile", 0.0) > 0.0

    def test_run_summary_census(self, telemetry_run):
        _, _, _, summary = telemetry_run
        assert summary["compile_seconds"] > 0.0
        coll = summary["collectives"]
        assert set(coll) == {"all-reduce", "all-gather", "reduce-scatter",
                             "collective-permute", "all-to-all"}
        assert sum(coll.values()) > 0  # tp=2 + sp inserts real collectives
        mem = summary["memory_analysis"]
        assert mem["peak_bytes"] > 0
        assert {"temp_size_in_bytes", "argument_size_in_bytes",
                "output_size_in_bytes"} <= set(mem)
        # the analytic FLOPs model the MFU derives from, both conventions
        assert summary["train_step_flops_per_token"] == pytest.approx(
            3.0 * summary["fwd_flops_per_token"])
        assert summary["model_family"] == "LlamaConfig"
        assert summary["n_chips"] == 8
        assert summary["seq_len"] == 32

    def test_goodput_summary_written(self, telemetry_run):
        _, _, _, summary = telemetry_run
        gp = summary["goodput"]
        assert 0.0 <= gp["goodput_fraction"] <= 1.0
        assert gp["productive_seconds"] + gp["nonproductive_seconds"] == (
            pytest.approx(gp["wall_seconds"], rel=0.05))
        assert "compile" in gp["breakdown_seconds"]

    def test_census_swapped_in_aot_executable(self, telemetry_run):
        # the census AOT-compiles once and the loop runs THAT executable:
        # no .lower means no second (jit-cache) compile ever happened
        t, _, _, _ = telemetry_run
        assert not hasattr(t.train_step, "lower")

    def test_step_time_excludes_compile(self, telemetry_run):
        # the old step_timed folded the first compile into the first window;
        # now the first boundary's step_time must be of the same order as
        # later steady-state steps, not compile-sized
        _, _, records, summary = telemetry_run
        boundary = [r for r in records if "step_time" in r]
        assert boundary[0]["step_time"] < summary["compile_seconds"]


class TestCensusOffCompileClassification:
    def test_first_jit_dispatch_counts_as_compile(self, tmp_path, devices8):
        """With compile_census off the first jit call traces+compiles inline;
        that wall time must land in time/compile (excluded from throughput
        and goodput), not in productive dispatch — the knob interaction must
        not silently reintroduce the contamination this PR removes."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _tiny_cfg(
            tmp_path,
            exp_manager={"exp_dir": str(tmp_path / "exp"),
                         "create_tensorboard_logger": False,
                         "log_files": False,
                         "telemetry": {"compile_census": False}},
        )
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        t.fit()
        assert hasattr(t.train_step, "lower")  # census off: still the jit fn
        exp_dir = tmp_path / "exp" / "tel" / "version_0"
        records = [json.loads(l) for l in
                   (exp_dir / "metrics.jsonl").read_text().strip().splitlines()]
        assert not (exp_dir / "run_summary.json").exists() or \
            "collectives" not in json.loads(
                (exp_dir / "run_summary.json").read_text())
        boundary = [r for r in records if "step_time" in r]
        first = boundary[0]
        assert first.get("time/compile", 0.0) > 0.0
        # compile dominates the first window; step_time must not absorb it
        assert first["step_time"] < first["time/compile"]


class TestDispatchAheadContract:
    def test_no_host_sync_between_boundaries(self, tmp_path, devices8):
        """Telemetry must add ZERO host syncs between logging boundaries:
        with an instrumented step, metric values are only ever converted to
        host floats at boundary steps."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _tiny_cfg(
            tmp_path,
            trainer={"max_steps": 6, "log_every_n_steps": 3},
        )
        t = Trainer.from_config(cfg, enable_checkpointing=False)

        conversions: list[int] = []

        class _Scalar:
            def __init__(self, step):
                self.step = step

            def __float__(self):
                conversions.append(self.step)
                return 1.0

        real_params, real_opt = t.params, t.opt_state

        def fake_step(params, opt_state, batch, key):
            # pure host-side stand-in: any float() of its metrics IS a sync
            return real_params, real_opt, {"loss": _Scalar(t.step),
                                           "grad_norm": _Scalar(t.step)}

        t.train_step = fake_step
        t.fit()
        # metrics were fetched only at the boundary steps (pre-increment
        # step ids 2 and 5 -> boundaries at steps 3 and 6)
        assert conversions, "boundaries must fetch metrics"
        assert set(conversions) == {2, 5}, conversions


# ---------------------------------------------------------------------------
# tools/metrics_report.py smoke
# ---------------------------------------------------------------------------


def _load_metrics_report():
    path = Path(__file__).resolve().parents[1] / "tools" / "metrics_report.py"
    spec = importlib.util.spec_from_file_location("metrics_report", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestMetricsReport:
    def test_renders_run_dir(self, tmp_path, capsys):
        mr = _load_metrics_report()
        with open(tmp_path / "metrics.jsonl", "w") as f:
            for s in (2, 4):
                f.write(json.dumps({"step": s, "loss": 7.0 - s, "mfu": 0.5,
                                    "goodput_fraction": 0.9}) + "\n")
        with open(tmp_path / "run_summary.json", "w") as f:
            json.dump({"compile_seconds": 3.0,
                       "collectives": {"all-reduce": 2},
                       "memory_analysis": {"peak_bytes": 2048},
                       "goodput": {"goodput_fraction": 0.91,
                                   "wall_seconds": 10.0,
                                   "breakdown_seconds": {"compile": 0.9}}}, f)
        assert mr.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        for needle in ("mfu", "goodput_fraction", "steps 2..4",
                       "compile_seconds", "all-reduce=2", "2.0 KiB",
                       "goodput"):
            assert needle in out, (needle, out)

    def test_missing_path_errors(self, tmp_path):
        mr = _load_metrics_report()
        assert mr.main([str(tmp_path / "nope")]) == 2

    def test_renders_real_run_output(self, telemetry_run, tmp_path, capsys):
        # the renderer must accept exactly what the trainer writes
        mr = _load_metrics_report()
        t, _, _, _ = telemetry_run
        assert mr.main([str(t.exp.log_dir)]) == 0
        out = capsys.readouterr().out
        assert "mfu" in out and "compile census" in out
