"""Tensor numerics observatory (telemetry/tensorstats + quant_readiness +
the optimizer/trainer wiring): config validation, the packed cumulative
state + its sharding specs, in-graph stat exactness (absmax/rms/zero and
subnormal fractions/log2-exponent histogram, NaN/inf edge handling), the
pure-observer contract (bitwise-unchanged update, bitwise no-op when off),
a real tiny-llama train step, the fit()-level overhead contract with
health + fleet + alerts + bucketed overlap riding alongside (AOT once, zero
retraces, zero extra host syncs), resume from a pre-tensorstats checkpoint,
and the block-scaled int8 quantization-readiness model with hand-computed
SQNR pins + the tools/quant_readiness.py CLI over the committed fixture —
all tier-1 / CPU."""

import importlib.util
import json
import math
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from neuronx_distributed_training_tpu.optim.adamw import (
    AdamWConfig,
    adamw_update,
    init_opt_state,
    opt_state_specs,
)
from neuronx_distributed_training_tpu.telemetry import (
    TelemetryConfig,
    grad_group_of,
)
from neuronx_distributed_training_tpu.telemetry.tensorstats import (
    CUM_HEADER,
    HIST_PREFIX,
    SCALAR_PREFIX,
    TensorStatsConfig,
    decode_cum,
    init_tensorstats_state,
    split_state_key,
    state_key,
    tensorstats_state_specs,
    tensorstats_update,
)
from neuronx_distributed_training_tpu.telemetry.quant_readiness import (
    build_report,
    bytes_saved_fraction,
    load_run_dir,
    pool_groups,
    predict_block_quant,
)
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

FIXTURE = Path(__file__).resolve().parent / "data" / "quant_readiness_fixture"


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------


class TestTensorStatsConfig:
    def test_defaults_disabled(self):
        ts = TelemetryConfig.from_config(None).tensorstats
        assert ts.enabled is False
        assert ts.pre_clip is True and ts.post_clip is True
        assert ts.buckets is False
        assert (ts.hist_lo_exp, ts.hist_hi_exp) == (-24, 8)
        assert ts.nbins == 33
        assert ts.vec_len == len(CUM_HEADER) + 33

    def test_bare_bool_enables(self):
        assert TensorStatsConfig.from_config(True).enabled is True
        assert TensorStatsConfig.from_config(False).enabled is False

    def test_unknown_key_rejected_at_load(self):
        from neuronx_distributed_training_tpu.config.loader import load_config

        cfg = {"exp_manager": {"telemetry": {"tensorstats": {"enabld": True}}},
               "data": {"global_batch_size": 8, "micro_batch_size": 1}}
        with pytest.raises(ValueError, match="enabld"):
            load_config(cfg)

    def test_did_you_mean(self):
        with pytest.raises(ValueError, match="pre_clip"):
            TensorStatsConfig.from_config({"pre_clp": True})

    def test_bad_values_rejected(self):
        with pytest.raises(ValueError, match="boolean"):
            TensorStatsConfig.from_config({"enabled": "yes"})
        with pytest.raises(ValueError, match="integer"):
            TensorStatsConfig.from_config({"hist_lo_exp": "low"})
        with pytest.raises(ValueError, match="integer"):
            TensorStatsConfig.from_config({"hist_hi_exp": True})
        with pytest.raises(ValueError, match="hist_hi_exp"):
            TensorStatsConfig.from_config({"hist_lo_exp": 4, "hist_hi_exp": 4})
        with pytest.raises(ValueError, match="256"):
            TensorStatsConfig.from_config({"hist_lo_exp": -300,
                                           "hist_hi_exp": 8})

    def test_enabled_with_all_phases_off_rejected(self):
        with pytest.raises(ValueError, match="nothing to record"):
            TensorStatsConfig.from_config({"enabled": True, "pre_clip": False,
                                           "post_clip": False,
                                           "buckets": False})

    def test_blanket_telemetry_true_keeps_tensorstats_disabled(self):
        # like health: enabling it changes the opt-state tree (and therefore
        # checkpoints), so a blanket bool must never opt in silently
        assert TelemetryConfig.from_config(True).tensorstats.enabled is False
        assert TelemetryConfig.from_config(False).tensorstats.enabled is False

    def test_round_trip_through_loader(self):
        from neuronx_distributed_training_tpu.config.loader import load_config

        cfg = load_config({
            "exp_manager": {"telemetry": {"tensorstats": {
                "enabled": True, "post_clip": False, "buckets": True,
                "hist_lo_exp": -16, "hist_hi_exp": 4}}},
            "data": {"global_batch_size": 8, "micro_batch_size": 1},
        })
        ts = TelemetryConfig.from_config(
            cfg["exp_manager"]["telemetry"]).tensorstats
        assert ts.enabled and not ts.post_clip and ts.buckets
        assert (ts.hist_lo_exp, ts.hist_hi_exp) == (-16, 4)
        assert ts.nbins == 21


# ---------------------------------------------------------------------------
# state layout + sharding specs
# ---------------------------------------------------------------------------


def _params():
    k = jax.random.PRNGKey(0)
    return {
        "embed": {"embedding": jax.random.normal(k, (16, 8))},
        "layers": {
            "attn": {"qkv": {"w": jax.random.normal(k, (2, 8, 8))}},
            "mlp": {"down": {"w": jax.random.normal(k, (2, 8, 8))}},
            "input_norm": {"scale": jnp.ones((2, 8))},
        },
        "final_norm": {"scale": jnp.ones((8,))},
    }


_GROUPS = {"embed", "layers/attn", "layers/mlp", "layers/input_norm",
           "final_norm"}


def _trees_bitwise_equal(a, b) -> bool:
    return bool(jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda x, y: bool(jnp.array_equal(x, y, equal_nan=True)), a, b)))


class TestTensorStatsState:
    def test_state_key_round_trip(self):
        # checkpoint path naming must not see "/" — state keys use "."
        assert state_key("pre", "layers/attn") == "pre.layers.attn"
        assert split_state_key("pre.layers.attn") == ("pre", "layers/attn")
        assert split_state_key(state_key("bucket", "g0")) == ("bucket", "g0")

    def test_init_state_layout(self):
        cfg = TensorStatsConfig(enabled=True, buckets=True)
        state = init_tensorstats_state(cfg, _params(), bucket_groups=("b0",))
        expect = ({"steps"}
                  | {state_key("pre", g) for g in _GROUPS}
                  | {state_key("post", g) for g in _GROUPS}
                  | {state_key("bucket", "b0")})
        assert set(state) == expect
        assert state["steps"].dtype == jnp.int32
        for k, v in state.items():
            if k != "steps":
                assert v.shape == (cfg.vec_len,) and v.dtype == jnp.float32

    def test_phase_knobs_prune_slots(self):
        cfg = TensorStatsConfig(enabled=True, post_clip=False)
        state = init_tensorstats_state(cfg, _params())
        assert not any(k.startswith("post.") for k in state)
        assert any(k.startswith("pre.") for k in state)
        assert not any(k.startswith("bucket.") for k in state)

    def test_opt_state_and_specs_structure_match(self, cpu_mesh):
        from jax.sharding import PartitionSpec as P

        cfg = TensorStatsConfig(enabled=True, buckets=True)
        params = _params()
        state = init_opt_state(params, tensorstats=cfg,
                               tensorstats_bucket_groups=("b0",))
        assert "tensorstats" in state
        pspecs = jax.tree_util.tree_map(lambda _: P(), params)
        ospecs = opt_state_specs(params, pspecs, cpu_mesh, tensorstats=cfg,
                                 tensorstats_bucket_groups=("b0",))
        # spec tree structure must match the state tree structure exactly
        assert (jax.tree_util.tree_structure(state)
                == jax.tree_util.tree_structure(
                    jax.tree_util.tree_map(
                        lambda x: x, ospecs,
                        is_leaf=lambda x: isinstance(x, P))))
        assert ospecs["tensorstats"] == tensorstats_state_specs(
            cfg, params, bucket_groups=("b0",))

    def test_disabled_adds_no_subtree(self, cpu_mesh):
        from jax.sharding import PartitionSpec as P

        params = _params()
        assert "tensorstats" not in init_opt_state(
            params, tensorstats=TensorStatsConfig(enabled=False))
        pspecs = jax.tree_util.tree_map(lambda _: P(), params)
        assert "tensorstats" not in opt_state_specs(
            params, pspecs, cpu_mesh,
            tensorstats=TensorStatsConfig(enabled=False))


# ---------------------------------------------------------------------------
# in-graph stat exactness
# ---------------------------------------------------------------------------


class TestStatExactness:
    def _update(self, cfg, grads, state=None, **kw):
        if state is None:
            state = init_tensorstats_state(cfg, groups=["g"])
        return tensorstats_update(state, cfg, group_fn=lambda p: "g",
                                  grads_pre=grads, **kw)

    def test_hand_computed_stats(self):
        cfg = TensorStatsConfig(enabled=True, post_clip=False)
        grads = {"a": jnp.full((8,), 0.125, jnp.float32),
                 "b": jnp.zeros((4,), jnp.float32)}
        state, m = self._update(cfg, grads)
        base = f"{SCALAR_PREFIX}pre/g"
        assert float(m[f"{base}/absmax"]) == 0.125
        # rms over ALL 12 elements: sqrt(8 * 0.125^2 / 12)
        assert float(m[f"{base}/rms"]) == pytest.approx(
            math.sqrt(8 * 0.125 ** 2 / 12), rel=1e-6)
        assert float(m[f"{base}/zero_frac"]) == pytest.approx(4 / 12)
        assert float(m[f"{base}/subnormal_frac"]) == 0.0
        rec = decode_cum(np.asarray(state[state_key("pre", "g")]), cfg)
        assert rec["count"] == 12 and rec["zero"] == 4
        # floor(log2 0.125) = -3 -> bin -3 - (-24) = 21 holds the 8 values
        assert rec["hist"][-3 - cfg.hist_lo_exp] == 8
        assert sum(rec["hist"]) == 8

    def test_subnormal_and_inf_edges(self):
        cfg = TensorStatsConfig(enabled=True, post_clip=False)
        # 1e-40 is f32-subnormal (tiny ~1.18e-38).  Backends with
        # flush-to-zero arithmetic (XLA CPU among them) see it as an exact
        # zero, so the two small values land in EITHER the zero or the
        # subnormal fraction — never dropped, never double-counted.
        # +/-inf always lands in the top histogram bin.
        grads = {"a": jnp.asarray([0.0, 1e-40, -1e-40, jnp.inf],
                                  jnp.float32)}
        state, m = self._update(cfg, grads)
        base = f"{SCALAR_PREFIX}pre/g"
        zf = float(m[f"{base}/zero_frac"])
        sf = float(m[f"{base}/subnormal_frac"])
        assert zf + sf == pytest.approx(3 / 4)
        assert zf >= 1 / 4  # the true zero is a zero everywhere
        assert math.isinf(float(m[f"{base}/absmax"]))
        rec = decode_cum(np.asarray(state[state_key("pre", "g")]), cfg)
        assert rec["hist"][-1] == 1         # inf in the top bin
        # subnormals (when not flushed) clip into the bottom bin
        assert rec["hist"][0] == rec["subnormal"]
        assert sum(rec["hist"]) == 1 + rec["subnormal"]
        # the non-finite sumsq/absmax step contribution was dropped by the
        # cumulative merge (a poisoned step must not poison the whole run)
        assert math.isfinite(rec["absmax"]) and math.isfinite(rec["sumsq"])

    def test_subnormal_slot_decodes(self):
        # the decode side of the subnormal fraction, independent of backend
        # flush-to-zero behavior: hand-pack a cumulative vector
        cfg = TensorStatsConfig(enabled=True)
        vec = [0.0] * cfg.vec_len
        vec[0], vec[1], vec[2], vec[3], vec[4] = 8.0, 1.0, 0.5, 2.0, 3.0
        rec = decode_cum(vec, cfg)
        assert rec["zero_frac"] == pytest.approx(2 / 8)
        assert rec["subnormal_frac"] == pytest.approx(3 / 8)
        assert rec["rms"] == pytest.approx(math.sqrt(1.0 / 8))

    def test_nan_excluded_from_hist_and_sanitized_in_cum(self):
        cfg = TensorStatsConfig(enabled=True, post_clip=False)
        grads = {"a": jnp.asarray([jnp.nan, 0.5], jnp.float32)}
        state, m = self._update(cfg, grads)
        # per-step scalars stay honest: the NaN poisons absmax/rms
        assert math.isnan(float(m[f"{SCALAR_PREFIX}pre/g/absmax"]))
        rec = decode_cum(np.asarray(state[state_key("pre", "g")]), cfg)
        assert sum(rec["hist"]) == 1        # only the 0.5 binned
        assert rec["absmax"] == 0.5 or rec["absmax"] == 0.0
        assert math.isfinite(rec["sumsq"])

    def test_cumulative_over_steps(self):
        cfg = TensorStatsConfig(enabled=True, post_clip=False)
        g1 = {"a": jnp.full((8,), 0.125, jnp.float32)}
        g2 = {"a": jnp.full((8,), 0.5, jnp.float32)}
        state, _ = self._update(cfg, g1)
        state, m = self._update(cfg, g2, state=state)
        assert int(state["steps"]) == 2
        rec = decode_cum(np.asarray(state[state_key("pre", "g")]), cfg)
        assert rec["count"] == 16
        assert rec["absmax"] == 0.5         # running max across steps
        assert rec["sumsq"] == pytest.approx(8 * 0.125 ** 2 + 8 * 0.5 ** 2)
        assert rec["hist"][-3 - cfg.hist_lo_exp] == 8
        assert rec["hist"][-1 - cfg.hist_lo_exp] == 8
        # the HIST_PREFIX metric IS the cumulative vector
        assert np.array_equal(np.asarray(m[f"{HIST_PREFIX}pre/g"]),
                              np.asarray(state[state_key("pre", "g")]))

    def test_group_sq_override_shares_clip_reduction(self):
        # the pre-clip rms must reuse the clipping norm's squared sums, not
        # recompute them: an override value shows up verbatim in the rms
        cfg = TensorStatsConfig(enabled=True, post_clip=False)
        grads = {"a": jnp.full((12,), 0.125, jnp.float32)}
        _, m = self._update(cfg, grads,
                            group_sq={"g": jnp.asarray(999.0, jnp.float32)})
        assert float(m[f"{SCALAR_PREFIX}pre/g/rms"]) == pytest.approx(
            math.sqrt(999.0 / 12), rel=1e-6)

    def test_unknown_group_slot_raises(self):
        cfg = TensorStatsConfig(enabled=True, post_clip=False)
        state = init_tensorstats_state(cfg, groups=["g"])
        with pytest.raises(KeyError, match="disagree"):
            tensorstats_update(state, cfg, group_fn=lambda p: "h",
                               grads_pre={"a": jnp.ones((2,))})


# ---------------------------------------------------------------------------
# adamw integration: the pure-observer contract
# ---------------------------------------------------------------------------


class TestAdamWTensorStats:
    def test_update_bitwise_unchanged_by_observer(self):
        params = _params()
        grads = jax.tree_util.tree_map(lambda p: 0.1 * p, params)
        cfg = TensorStatsConfig(enabled=True)
        o1 = init_opt_state(params)
        o2 = init_opt_state(params, tensorstats=cfg)
        # both runs use the grouped-norm path (tensorstats forces it on), so
        # the update math is instruction-for-instruction the same
        p1, s1, _ = adamw_update(params, grads, o1, 1e-3, AdamWConfig(),
                                 grad_group_fn=grad_group_of)
        p2, s2, _ = adamw_update(params, grads, o2, 1e-3, AdamWConfig(),
                                 tensorstats_cfg=cfg)
        assert _trees_bitwise_equal(p1, p2)
        assert _trees_bitwise_equal(
            s1, {k: v for k, v in s2.items() if k != "tensorstats"})

    def test_metrics_emitted_per_phase_and_group(self):
        params = _params()
        grads = jax.tree_util.tree_map(lambda p: 0.1 * p, params)
        cfg = TensorStatsConfig(enabled=True)
        opt = init_opt_state(params, tensorstats=cfg)
        _, s, m = adamw_update(params, grads, opt, 1e-3, AdamWConfig(),
                               tensorstats_cfg=cfg)
        ts = m["tensorstats"]
        for phase in ("pre", "post"):
            for g in _GROUPS:
                for stat in ("absmax", "rms", "zero_frac", "subnormal_frac"):
                    assert f"{SCALAR_PREFIX}{phase}/{g}/{stat}" in ts
                hv = ts[f"{HIST_PREFIX}{phase}/{g}"]
                assert hv.shape == (cfg.vec_len,)
        assert int(s["tensorstats"]["steps"]) == 1

    def test_post_clip_sees_clipped_grads(self):
        params = _params()
        # huge grads so the clip actually bites
        grads = jax.tree_util.tree_map(lambda p: 100.0 * p, params)
        cfg = TensorStatsConfig(enabled=True)
        opt = init_opt_state(params, tensorstats=cfg)
        acfg = AdamWConfig(grad_clip_norm=1.0)
        _, _, m = adamw_update(params, grads, opt, 1e-3, acfg,
                               tensorstats_cfg=cfg)
        ts = m["tensorstats"]
        pre = float(ts[f"{SCALAR_PREFIX}pre/embed/absmax"])
        post = float(ts[f"{SCALAR_PREFIX}post/embed/absmax"])
        assert post < pre  # the clip shrank the observed magnitudes

    def test_skipped_step_reverts_observer_state_too(self):
        # skip_nonfinite must keep the WHOLE donated opt state bitwise equal
        # — including the tensorstats record (the skipped step contributed
        # nothing; the per-step scalars still showed the event)
        params = _params()
        grads = jax.tree_util.tree_map(lambda p: 0.1 * p, params)
        grads["embed"]["embedding"] = (
            grads["embed"]["embedding"].at[0, 0].set(jnp.nan))
        cfg = TensorStatsConfig(enabled=True)
        opt = init_opt_state(params, tensorstats=cfg)
        _, s, m = adamw_update(params, grads, opt, 1e-3, AdamWConfig(),
                               skip_nonfinite=True, tensorstats_cfg=cfg)
        assert not bool(m["updates_finite"])
        assert _trees_bitwise_equal(s, opt)

    def test_disabled_cfg_is_inert(self):
        params = _params()
        grads = jax.tree_util.tree_map(lambda p: 0.1 * p, params)
        opt = init_opt_state(params)
        _, s, m = adamw_update(params, grads, opt, 1e-3, AdamWConfig(),
                               tensorstats_cfg=TensorStatsConfig(
                                   enabled=False))
        assert "tensorstats" not in m and "tensorstats" not in s


# ---------------------------------------------------------------------------
# make_train_step: the observatory on a real tiny llama step
# ---------------------------------------------------------------------------


def _llama_step(ts_cfg):
    from neuronx_distributed_training_tpu.models import llama
    from neuronx_distributed_training_tpu.optim.lr import constant_lr
    from neuronx_distributed_training_tpu.telemetry import HealthConfig
    from neuronx_distributed_training_tpu.trainer.step import make_train_step

    cfg = llama.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=2,
        num_attention_heads=4, num_kv_heads=2, max_position_embeddings=16)
    policy = DtypePolicy()
    params = llama.init_params(jax.random.PRNGKey(0), cfg, policy)
    hc = HealthConfig(enabled=True, policy="skip_update")
    opt = init_opt_state(params, policy, health=True, tensorstats=ts_cfg)

    def loss_fn(p, batch, key):
        return llama.forward(p, batch, cfg, policy)

    step = jax.jit(make_train_step(
        loss_fn, AdamWConfig(), constant_lr(1e-3), policy, health_cfg=hc,
        tensorstats_cfg=ts_cfg))
    ids = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64,
                             dtype=jnp.int32)
    batch = {"input_ids": ids, "labels": ids,
             "loss_mask": jnp.ones((4, 16), jnp.float32)}
    return step, params, opt, batch


class TestTrainStepTensorStats:
    def test_stats_ride_the_one_jitted_step(self):
        ts_cfg = TensorStatsConfig(enabled=True)
        step, params, opt, batch = _llama_step(ts_cfg)
        _, o1, m = step(params, opt, batch, jax.random.PRNGKey(2))
        assert float(m["health/updates_finite"]) == 1.0
        # metric keys keep the "/" group spelling (state keys use ".")
        assert f"{SCALAR_PREFIX}pre/layers/attn/absmax" in m
        assert f"{SCALAR_PREFIX}post/embed/rms" in m
        hist = {k for k in m if k.startswith(HIST_PREFIX)}
        assert f"{HIST_PREFIX}pre/embed" in hist
        assert np.asarray(m[f"{HIST_PREFIX}pre/embed"]).shape == (
            ts_cfg.vec_len,)
        assert int(o1["tensorstats"]["steps"]) == 1
        # rms consistency with the health grad-norm plane: same reduction
        g = "layers/attn"
        rec = decode_cum(np.asarray(m[f"{HIST_PREFIX}pre/{g}"]), ts_cfg)
        np.testing.assert_allclose(
            math.sqrt(rec["sumsq"]), float(m[f"health/grad_norm/{g}"]),
            rtol=1e-5)

    def test_disabled_adds_no_keys(self):
        step, params, opt, batch = _llama_step(
            TensorStatsConfig(enabled=False))
        _, o, m = step(params, opt, batch, jax.random.PRNGKey(2))
        assert not any(k.startswith(SCALAR_PREFIX) for k in m)
        assert not any(k.startswith(HIST_PREFIX) for k in m)
        assert "tensorstats" not in o


# ---------------------------------------------------------------------------
# fit()-level contract: observatory + health + fleet + alerts + bucketed
# overlap, all riding ONE compiled step with zero extra host syncs
# ---------------------------------------------------------------------------


def _ts_cfg(tmp_path, *, max_steps=6, log_every=1):
    from neuronx_distributed_training_tpu.config.loader import load_config

    return load_config({
        "name": "tstats", "model_source": "hf", "seed": 7,
        "trainer": {"max_steps": max_steps, "log_every_n_steps": log_every},
        "exp_manager": {"exp_dir": str(tmp_path / "exp"),
                        "create_tensorboard_logger": False,
                        "log_files": False,
                        "telemetry": {
                            "health": {"enabled": True,
                                       "policy": "skip_update",
                                       "ring_buffer_steps": 8},
                            "tensorstats": {"enabled": True,
                                            "buckets": True},
                            "fleet": {"enabled": True,
                                      "stale_after_seconds": 600},
                            "alerts": [{"metric":
                                        "tensorstats/pre/embed/rms",
                                        "rel_rise": 1000.0,
                                        "action": "log"}],
                        }},
        "distributed_strategy": {"tensor_model_parallel_size": 2,
                                 "sequence_parallel": True, "zero1": True,
                                 "overlap": {"zero1_bucket_mb": 0.0625,
                                             "prefetch_ag": True}},
        "data": {"global_batch_size": 8, "micro_batch_size": 1,
                 "seq_length": 32, "synthetic": True},
        "model": {"vocab_size": 128, "hidden_size": 64,
                  "intermediate_size": 128, "num_layers": 2,
                  "num_attention_heads": 4, "num_key_value_heads": 2,
                  "max_position_embeddings": 32,
                  "optim": {"name": "adamw_fp32OptState", "lr": 1e-3}},
        "precision": {"type": "mixed_precision"},
    })


def _data_module():
    from neuronx_distributed_training_tpu.data import SyntheticDataModule

    return SyntheticDataModule(vocab_size=128, seq_len=32,
                               global_batch_size=8, seed=3)


class TestFitContract:
    @pytest.fixture(scope="class")
    def observatory_run(self, tmp_path_factory, devices8):
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        tmp_path = tmp_path_factory.mktemp("tstats")
        cfg = _ts_cfg(tmp_path)
        t = Trainer.from_config(cfg, data_module=_data_module(),
                                enable_checkpointing=False)
        metrics = t.fit()
        return t, metrics, Path(t.exp.log_dir)

    def test_aot_once_zero_retraces(self, observatory_run):
        t, _, log_dir = observatory_run
        assert not hasattr(t.train_step, "lower")
        summary = json.loads((log_dir / "run_summary.json").read_text())
        assert "retrace_events" not in summary
        assert "anomalies" not in summary

    def test_scalars_in_metrics_jsonl_hist_routed_around(self,
                                                         observatory_run):
        _, _, log_dir = observatory_run
        records = [json.loads(l) for l in
                   (log_dir / "metrics.jsonl").read_text().splitlines()]
        last = records[-1]
        assert any(k.startswith(f"{SCALAR_PREFIX}pre/") for k in last)
        assert any(k.startswith(f"{SCALAR_PREFIX}post/") for k in last)
        assert any(k.startswith(f"{SCALAR_PREFIX}bucket/") for k in last)
        # the packed vectors must NEVER reach the scalar stream
        assert not any(k.startswith(HIST_PREFIX) for r in records for k in r)
        # health rides alongside, unchanged
        assert last["health/updates_finite"] == 1.0

    def test_tensorstats_jsonl_cumulates(self, observatory_run):
        _, _, log_dir = observatory_run
        lines = (log_dir / "tensorstats.jsonl").read_text().splitlines()
        records = [json.loads(l) for l in lines]
        for l in lines:  # strict JSON: no bare NaN/Infinity tokens
            json.dumps(json.loads(l), allow_nan=False)
        assert [r["step"] for r in records] == [1, 2, 3, 4, 5, 6]
        first, last = records[0], records[-1]
        assert "pre/embed" in last["groups"]
        assert any(k.startswith("bucket/") for k in last["groups"])
        # the cumulative count grows linearly with steps
        assert last["groups"]["pre/embed"]["count"] == pytest.approx(
            6 * first["groups"]["pre/embed"]["count"])
        # absmax is a running max: monotone non-decreasing across records
        trail = [r["groups"]["pre/embed"]["absmax"] for r in records]
        assert trail == sorted(trail)

    def test_run_summary_section(self, observatory_run):
        _, _, log_dir = observatory_run
        summary = json.loads((log_dir / "run_summary.json").read_text())
        ts = summary["tensorstats"]
        assert ts["step"] == 6
        assert ts["hist_lo_exp"] == -24 and ts["hist_hi_exp"] == 8
        assert set(ts["groups"]) >= {"pre/embed", "post/embed"}
        # ...and it is exactly what quant readiness consumes
        inputs = load_run_dir(log_dir)
        report = build_report(inputs["tensorstats"])
        assert report["classes"]["reduce-scatter"]["pooled"]

    def test_beacons_carry_tensorstats(self, observatory_run):
        _, _, log_dir = observatory_run
        beacon = next((log_dir / "fleet").glob("host_*.jsonl"))
        records = [json.loads(l) for l in
                   beacon.read_text().splitlines()]
        # the final line is the metrics-less closing record; the boundary
        # beacons before it must carry the per-step scalars (and never the
        # packed vectors)
        boundary = [r for r in records if not r.get("closing")]
        assert boundary
        assert all(any(k.startswith(SCALAR_PREFIX) for k in r["metrics"])
                   for r in boundary)
        assert not any(k.startswith(HIST_PREFIX)
                       for r in records for k in r["metrics"])

    def test_quant_readiness_runs_on_fresh_artifacts(self, observatory_run,
                                                     capsys):
        _, _, log_dir = observatory_run
        qr = _load_tool("quant_readiness")
        assert qr.main([str(log_dir), "--json", "-"]) == 0
        out = capsys.readouterr().out
        payload = json.loads(out.strip().splitlines()[-1])
        assert payload["ok"] is True
        assert "reduce-scatter" in payload["classes"]


class TestDispatchAheadContractWithTensorstats:
    def test_no_host_sync_between_boundaries(self, tmp_path, devices8):
        """The observatory must add ZERO host syncs between boundaries: the
        per-step scalars are converted to host floats only at boundary steps
        and the packed vectors bypass float() entirely."""
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _ts_cfg(tmp_path, max_steps=6, log_every=3)
        t = Trainer.from_config(cfg, data_module=_data_module(),
                                enable_checkpointing=False)

        conversions: list[int] = []

        class _Scalar:
            def __init__(self, step, value=1.0):
                self.step, self.value = step, value

            def __float__(self):
                conversions.append(self.step)
                return self.value

        real_params, real_opt = t.params, t.opt_state
        vec_len = TensorStatsConfig(enabled=True).vec_len

        def fake_step(params, opt_state, batch, key):
            return real_params, real_opt, {
                "loss": _Scalar(t.step),
                "grad_norm": _Scalar(t.step),
                "health/updates_finite": _Scalar(t.step),
                "health/nonfinite_count": _Scalar(t.step, 0.0),
                "health/last_nonfinite_step": _Scalar(t.step, -1.0),
                f"{SCALAR_PREFIX}pre/embed/absmax": _Scalar(t.step, 0.5),
                f"{SCALAR_PREFIX}pre/embed/rms": _Scalar(t.step, 0.1),
                f"{HIST_PREFIX}pre/embed": np.zeros(vec_len, np.float32),
            }

        t.train_step = fake_step
        t.fit()
        assert conversions, "boundaries must fetch metrics"
        # pre-increment step ids 2 and 5 -> boundaries at steps 3 and 6; the
        # ring-buffered steps 0,1,3,4 must never have been fetched
        assert set(conversions) == {2, 5}, sorted(set(conversions))


class TestResumeCompat:
    def test_resume_from_pre_tensorstats_checkpoint(self, tmp_path, devices8):
        """Flipping tensorstats on must not strand an existing run: a
        checkpoint written WITHOUT the subtree restores with a fresh
        cumulative record — and KEEPS the health subtree it does carry
        (the strip-retry is narrowest-first)."""
        from neuronx_distributed_training_tpu.checkpoint import TrainState
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = _ts_cfg(tmp_path)
        t = Trainer.from_config(cfg, data_module=_data_module(),
                                enable_checkpointing=False)
        assert "tensorstats" in t.opt_state and "health" in t.opt_state

        class LegacyCheckpointer:
            """Restores a pre-tensorstats checkpoint: raises on a template
            that carries the tensorstats subtree (the orbax structure
            mismatch), but accepts health — like a real store from the
            previous release would."""

            config = type("C", (), {"every_n_train_steps": 0})

            def latest_step(self):
                return 4

            def restore(self, params, opt_state, **kw):
                if "tensorstats" in opt_state:
                    raise ValueError("tree structure mismatch: 'tensorstats'")
                return TrainState(params=params, opt_state=opt_state,
                                  step=4, consumed_samples=32)

            def wait(self):
                pass

            def close(self):
                pass

        t.checkpointer = LegacyCheckpointer()
        assert t.maybe_resume() is True
        assert t.step == 4
        # fresh observatory record re-attached; health survived the retry
        assert "tensorstats" in t.opt_state and "health" in t.opt_state
        assert int(t.opt_state["tensorstats"]["steps"]) == 0


# ---------------------------------------------------------------------------
# quantization-readiness model: hand-computed pins
# ---------------------------------------------------------------------------


def _single_bin(count=4096, exp=-3, lo=-24, nbins=33):
    hist = [0] * nbins
    hist[exp - lo] = count
    return hist


class TestQuantModel:
    def test_bytes_saved_fraction(self):
        # int8 payload + one fp32 scale per block, vs fp32 wire
        assert bytes_saved_fraction(32) == pytest.approx(0.71875)
        assert bytes_saved_fraction(128) == pytest.approx(0.7421875)
        assert bytes_saved_fraction(512) == pytest.approx(0.748046875)
        # vs a bf16 wire the win halves (scale amortized the same way)
        assert bytes_saved_fraction(128, 2.0) == pytest.approx(
            1.0 - 1.03125 / 2.0)
        with pytest.raises(ValueError, match="block_size"):
            bytes_saved_fraction(0)

    def test_uniform_single_bin_sqnr_exact(self):
        # every element 2^-3: block absmax exponent is -3 with certainty at
        # ANY block size, scale = 2^-2/127, SQNR = 12*127^2/4 ~= 46.847 dB
        expect = round(10 * math.log10(12 * 127 ** 2 / 4.0), 3)
        for b in (1, 32, 128, 512):
            p = predict_block_quant(_single_bin(), -24, count=4096.0,
                                    sumsq=64.0, block_size=b)
            assert p["sqnr_db"] == expect == 46.847
            assert p["rel_error_rms"] == pytest.approx(
                math.sqrt(4.0 / (12 * 127 ** 2)), rel=1e-6)

    def test_zero_mass_blocks(self):
        # half the elements exact zeros, half 2^-3.  B=1: all-zero "blocks"
        # contribute no noise AND no signal — SQNR is unchanged vs no zeros
        hist = _single_bin(count=2048)
        p1 = predict_block_quant(hist, -24, count=4096.0, sumsq=32.0,
                                 zero_count=2048.0, block_size=1)
        assert p1["sqnr_db"] == 46.847
        # B=2: only 1/4 of blocks are all-zero; noise weight 3/4 on the -3
        # scale against the same halved signal -> 12*127^2/6
        p2 = predict_block_quant(hist, -24, count=4096.0, sumsq=32.0,
                                 zero_count=2048.0, block_size=2)
        assert p2["sqnr_db"] == round(10 * math.log10(12 * 127 ** 2 / 6.0), 3)

    def test_spread_distribution_degrades_with_block_size(self):
        # two exponent bins 8 apart: larger blocks are dominated by the big
        # exponent's scale while half the mass is small -> SQNR decreases
        nbins = 33
        hist = [0] * nbins
        hist[-3 - (-24)] = 2048
        hist[-11 - (-24)] = 2048
        sumsq = 2048 * 2.0 ** -6 + 2048 * 2.0 ** -22
        sq = [predict_block_quant(hist, -24, count=4096.0, sumsq=sumsq,
                                  block_size=b)["sqnr_db"]
              for b in (1, 32, 512)]
        assert sq[0] > sq[1] >= sq[2]

    def test_degenerate_distributions(self):
        p = predict_block_quant([0] * 33, -24, count=0.0, sumsq=0.0)
        assert p["sqnr_db"] is None and p["rel_error_rms"] is None
        p = predict_block_quant([0] * 33, -24, count=64.0, sumsq=0.0,
                                zero_count=64.0)
        assert p["sqnr_db"] is None  # all zeros: nothing to quantize
        assert p["bytes_saved_frac"] == pytest.approx(0.7421875)

    def test_pool_groups(self):
        a = {"count": 4, "sumsq": 1.0, "zero": 1, "absmax": 0.5,
             "hist_lo_exp": -24, "hist_hi_exp": 8, "hist": _single_bin(3)}
        b = {"count": 2, "sumsq": 2.0, "zero": 0, "absmax": 2.0,
             "hist_lo_exp": -24, "hist_hi_exp": 8,
             "hist": _single_bin(2, exp=1)}
        pooled = pool_groups({"a": a, "b": b})
        assert pooled["count"] == 6 and pooled["sumsq"] == 3.0
        assert pooled["absmax"] == 2.0 and pooled["zero"] == 1
        assert pooled["hist"][-3 - (-24)] == 3
        assert pooled["hist"][1 - (-24)] == 2
        with pytest.raises(ValueError, match="pool"):
            pool_groups({"a": a, "b": dict(b, hist_lo_exp=-16)})

    def test_build_report_ranking_and_savings(self):
        ts = {"step": 3, "groups": {
            "pre/embed": {"count": 4096, "sumsq": 64.0, "zero": 0,
                          "absmax": 0.125, "hist_lo_exp": -24,
                          "hist_hi_exp": 8, "hist": _single_bin()}}}
        overlap = {"reduce-scatter": {"exposed_seconds": 0.2},
                   "all-reduce": {"wire_seconds": 0.05,
                                  "hidden_seconds": 0.04}}
        vols = {"tp": {"reduce-scatter": 1000.0, "all-gather": 1000.0},
                "pp": {"collective-permute": 500.0}}
        r = build_report(ts, byte_volumes=vols, overlap_by_class=overlap)
        rs = r["classes"]["reduce-scatter"]
        # savings priced at the LARGEST block size over measured exposure
        assert rs["block_size"] == 512
        assert rs["predicted_seconds_saved"] == pytest.approx(
            0.2 * 0.748046875)
        assert rs["bytes_saved_per_step"] == pytest.approx(
            1000.0 * 0.748046875)
        assert rs["pooled"]["512"]["sqnr_db"] == 46.847
        # exposed falls back to wire - hidden when unmeasured
        ar = r["classes"]["all-reduce"]
        assert ar["exposed_seconds"] == pytest.approx(0.01)
        # activation traffic: bytes only, error side marked unavailable
        cp = r["classes"]["collective-permute"]
        assert cp["phase"] is None and "activation" in cp["note"]
        assert r["ranking"][0] == "reduce-scatter"
        # the all-gather class had no bucket capture: note, not a crash
        assert "note" in r["classes"]["all-gather"]

    def test_build_report_without_telemetry(self):
        r = build_report(None, byte_volumes={"dp": {"all-reduce": 10.0}})
        assert r["step"] is None
        assert r["classes"]["all-reduce"]["bytes_saved_per_step"] > 0


# ---------------------------------------------------------------------------
# planner byte volumes (autotune.cost_model.collective_byte_volumes)
# ---------------------------------------------------------------------------


class TestByteVolumes:
    def test_matches_hand_math(self, tmp_path):
        from neuronx_distributed_training_tpu.autotune.cost_model import (
            collective_byte_volumes,
        )
        from neuronx_distributed_training_tpu.autotune.space import ModelFacts

        cfg = _ts_cfg(tmp_path)
        facts = ModelFacts.from_config(cfg)
        plan = facts.declared_plan_for(8)
        assert plan is not None and plan.tp == 2 and plan.dp == 4
        vols = collective_byte_volumes(facts, plan)
        # tp under SP: one AG/RS pair per 4 activations x hidden x bf16 x
        # fwd+bwd per layer; tokens_chip = 8*32/4 = 64
        layer_total = 4.0 * 64 * 64 * 2.0 * 2.0 * 2
        assert vols["tp"]["all-gather"] == pytest.approx(layer_total / 2)
        assert vols["tp"]["reduce-scatter"] == pytest.approx(layer_total / 2)
        # vocab-parallel CE: two [tokens] f32 all-reduces per microbatch
        assert vols["tp"]["all-reduce"] == pytest.approx(2.0 * 2.0 * 64 * 4.0)
        # ZeRO-1 dp splits into grad reduce-scatter + param all-gather
        assert set(vols["dp"]) == {"reduce-scatter", "all-gather"}
        assert all(v > 0 for v in vols["dp"].values())
        # the report accepts the axis-nested shape directly
        r = build_report(None, byte_volumes=vols)
        assert r["classes"]["reduce-scatter"]["bytes_per_step"] == (
            pytest.approx(layer_total / 2 + vols["dp"]["reduce-scatter"]))


# ---------------------------------------------------------------------------
# committed fixture + tools/quant_readiness.py CLI
# ---------------------------------------------------------------------------


def _load_tool(name):
    path = Path(__file__).resolve().parents[1] / "tools" / f"{name}.py"
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestQuantReadinessFixture:
    def test_fixture_internally_consistent(self):
        # the committed tensorstats.jsonl's LAST record must equal the
        # run_summary section — load_run_dir prefers the latter, the CLI
        # must behave the same whichever survives
        summary = json.loads((FIXTURE / "run_summary.json").read_text())
        last = json.loads(
            (FIXTURE / "tensorstats.jsonl").read_text().splitlines()[-1])
        assert last == summary["tensorstats"]

    def test_load_and_report(self):
        inputs = load_run_dir(FIXTURE)
        assert inputs["tensorstats"]["step"] == 6
        r = build_report(inputs["tensorstats"],
                         overlap_by_class=inputs["overlap_by_class"])
        # exposure 0.1 / 0.04 / 0.01 s -> savings rank in that order
        assert r["ranking"][:3] == ["reduce-scatter", "all-gather",
                                    "all-reduce"]
        rs = r["classes"]["reduce-scatter"]
        assert rs["predicted_seconds_saved"] == pytest.approx(
            0.1 * 0.748046875)
        # the all-2^-3 attn group pins the hand-computed SQNR exactly
        attn = rs["per_group"]["layers.attn"]
        assert attn["512"]["sqnr_db"] == 46.847
        # the underflow-heavy final_norm ranks worst of the pre groups
        per = {g: p["512"]["sqnr_db"] for g, p in rs["per_group"].items()}
        assert min(per, key=per.get) == "final_norm"
        # bucket phase feeds the all-gather class
        ag = r["classes"]["all-gather"]
        assert ag["pooled"]["512"]["sqnr_db"] == 46.847

    def test_missing_run_dir_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="tensorstats"):
            load_run_dir(tmp_path)

    def test_cli_smoke_json_last_line(self, capsys):
        qr = _load_tool("quant_readiness")
        assert qr.main([str(FIXTURE), "--json", "-"]) == 0
        out = capsys.readouterr().out
        assert "reduce-scatter" in out  # human-readable section
        payload = json.loads(out.strip().splitlines()[-1])
        assert payload["ok"] is True
        assert payload["ranking"][0] == "reduce-scatter"
        assert payload["classes"]["reduce-scatter"]["pooled"]

    def test_cli_error_path(self, tmp_path, capsys):
        qr = _load_tool("quant_readiness")
        assert qr.main([str(tmp_path), "--json", "-"]) == 2
        out = capsys.readouterr().out
        payload = json.loads(out.strip().splitlines()[-1])
        assert payload["ok"] is False and "tensorstats" in payload["error"]

    def test_cli_with_config_byte_volumes(self, tmp_path, capsys):
        import yaml

        cfg_path = tmp_path / "cfg.yaml"
        cfg_path.write_text(yaml.safe_dump({
            "name": "t", "model_source": "hf",
            "trainer": {"max_steps": 2, "devices": 8},
            "distributed_strategy": {"tensor_model_parallel_size": 2,
                                     "sequence_parallel": True,
                                     "zero1": True},
            "data": {"global_batch_size": 8, "micro_batch_size": 1,
                     "seq_length": 32, "synthetic": True},
            "model": {"vocab_size": 128, "hidden_size": 64,
                      "intermediate_size": 128, "num_layers": 2,
                      "num_attention_heads": 4, "num_key_value_heads": 2,
                      "max_position_embeddings": 32},
            "precision": {"type": "mixed_precision"},
        }))
        qr = _load_tool("quant_readiness")
        assert qr.main([str(FIXTURE), "--config", str(cfg_path),
                        "--json", "-"]) == 0
        out = capsys.readouterr().out
        payload = json.loads(out.strip().splitlines()[-1])
        assert payload["ok"] is True
        rs = payload["classes"]["reduce-scatter"]
        assert rs["bytes_per_step"] and rs["bytes_saved_per_step"] > 0


# ---------------------------------------------------------------------------
# tools/anomaly_report.py: the dynamic-range trail section
# ---------------------------------------------------------------------------


class TestAnomalyReportTensorstats:
    def test_trail_rendered_from_ring(self, tmp_path, capsys):
        from neuronx_distributed_training_tpu.telemetry import (
            HealthConfig,
            HealthMonitor,
        )

        mon = HealthMonitor(
            HealthConfig(enabled=True, ring_buffer_steps=8),
            dump_dir=tmp_path)
        for s in range(3):
            mon.record(s, {
                "loss": 1.0,
                "health/nonfinite_count": 0.0 if s < 2 else 1.0,
                f"{SCALAR_PREFIX}pre/embed/absmax": 0.5 + s,
                f"{SCALAR_PREFIX}pre/embed/rms": 0.1,
                f"{SCALAR_PREFIX}pre/embed/zero_frac": 0.0,
                f"{SCALAR_PREFIX}pre/embed/subnormal_frac": 0.25,
            })
        mon.check_boundary(3, {"health/nonfinite_count": 1.0,
                               "health/last_nonfinite_step": 2.0})
        ar = _load_tool("anomaly_report")
        assert ar.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "tensorstats absmax trail" in out
        assert "tensorstats dynamic range" in out
        assert "subnormal_frac" in out and "embed" in out
