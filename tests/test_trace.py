"""Device-time trace analytics (telemetry.trace / trace_analysis) + the
planner's measured-overlap calibration loop: knob validation, the Chrome-
trace parser on a committed fixture (overlapping intervals, multi-device
lanes, async -start/-done halves, unknown op names), the guarded global
profiler session (the double-stop teardown hazard), a live CPU-captured
trace through real tiny-llama ``fit()``, and cost-model ranking shifts when
the calibration changes — all tier-1 / CPU."""

import gzip
import importlib.util
import json
import shutil
from pathlib import Path

import pytest

from neuronx_distributed_training_tpu.telemetry import TraceConfig
from neuronx_distributed_training_tpu.telemetry import trace as trace_mod
from neuronx_distributed_training_tpu.telemetry import trace_analysis as ta
from neuronx_distributed_training_tpu.utils.debug import collective_kind_of

FIXTURE = Path(__file__).parent / "data" / "device_trace_fixture.trace.json"


@pytest.fixture(autouse=True)
def _reset_session_guard():
    """The profiler session guard is process-global state; tests must not
    leak an owner into each other."""
    trace_mod._SESSION_OWNER = None
    yield
    trace_mod._SESSION_OWNER = None


# ---------------------------------------------------------------------------
# collective-kind matching (census <-> trace analytics alignment)
# ---------------------------------------------------------------------------


class TestCollectiveKindOf:
    def test_plain_and_uniquified(self):
        assert collective_kind_of("all-reduce") == "all-reduce"
        assert collective_kind_of("all-reduce.17") == "all-reduce"
        assert collective_kind_of("reduce-scatter.3") == "reduce-scatter"
        assert collective_kind_of("collective-permute") == "collective-permute"

    def test_async_start_counts_done_does_not(self):
        # the same single-count convention as the HLO text census
        assert collective_kind_of("all-gather-start.4") == "all-gather"
        assert collective_kind_of("all-gather-done.4") is None
        assert collective_kind_of("all-reduce-done") is None

    def test_non_collectives(self):
        for name in ("dot.3", "fusion.12", "reduce.8", "reduce-window",
                     "all-reducer", "my-all-reduce", "while"):
            assert collective_kind_of(name) is None, name


# ---------------------------------------------------------------------------
# exp_manager.telemetry.trace knob validation
# ---------------------------------------------------------------------------


class TestTraceConfig:
    def test_defaults_disabled(self):
        tc = TraceConfig.from_config(None)
        assert not tc.enabled
        assert tc.start_step == 1 and tc.num_steps == 3 and not tc.keep_raw

    def test_bool_shortcut(self):
        assert TraceConfig.from_config(True).enabled
        assert not TraceConfig.from_config(False).enabled

    def test_unknown_key_did_you_mean(self):
        with pytest.raises(ValueError, match="start_step"):
            TraceConfig.from_config({"start_stepp": 2})

    def test_non_bool_rejected(self):
        with pytest.raises(ValueError, match="boolean"):
            TraceConfig.from_config({"keep_raw": "yes"})

    def test_window_bounds(self):
        with pytest.raises(ValueError, match="num_steps"):
            TraceConfig.from_config({"num_steps": 0})
        with pytest.raises(ValueError, match="start_step"):
            TraceConfig.from_config({"start_step": -1})

    def test_rejected_at_config_load(self):
        from neuronx_distributed_training_tpu.config.loader import load_config

        cfg = {"exp_manager": {"telemetry": {"trace": {"num_stepz": 2}}},
               "data": {"global_batch_size": 8, "micro_batch_size": 1}}
        with pytest.raises(ValueError, match="num_stepz"):
            load_config(cfg)

    def test_round_trip_through_telemetry_config(self):
        from neuronx_distributed_training_tpu.telemetry import TelemetryConfig

        tc = TelemetryConfig.from_config(
            {"trace": {"enabled": True, "start_step": 5, "num_steps": 2,
                       "keep_raw": True}})
        assert tc.trace == TraceConfig(enabled=True, start_step=5,
                                       num_steps=2, keep_raw=True)
        # blanket off leaves the opt-in trace block at its default
        assert not TelemetryConfig.from_config(False).trace.enabled


# ---------------------------------------------------------------------------
# the parser, on the committed fixture
# ---------------------------------------------------------------------------


@pytest.fixture()
def fixture_summary():
    return ta.analyze_events(
        json.loads(FIXTURE.read_text())["traceEvents"], top_k=10)


class TestTraceAnalysisFixture:
    def test_lane_and_name_filtering(self, fixture_summary):
        s = fixture_summary
        # 6 real device ops survive: runtime noise (::), unknown-cased
        # names, zero-duration events, -done halves, and host-lane events
        # with op-like names are all dropped
        assert s["num_op_events"] == 6
        assert s["devices"] == ["/device:TPU:0", "/device:TPU:1"]

    def test_overlap_merges_concurrent_compute(self, fixture_summary):
        # dev0 compute [0,100) and [80,180) merge to [0,180): the
        # all-reduce at [150,250) hides exactly 30us, not 50
        ar = fixture_summary["overlap_by_class"]["all-reduce"]
        assert ar["count"] == 2
        assert ar["wire_seconds"] == pytest.approx(130e-6)
        assert ar["hidden_seconds"] == pytest.approx(60e-6)
        assert ar["exposed_seconds"] == pytest.approx(70e-6)
        assert ar["achieved_overlap"] == pytest.approx(60 / 130, abs=1e-6)

    def test_multi_device_lanes_do_not_cross_hide(self, fixture_summary):
        # the all-gather on dev0 [300,350) has no concurrent dev0 compute;
        # dev1's compute must not hide it
        ag = fixture_summary["overlap_by_class"]["all-gather"]
        assert ag["wire_seconds"] == pytest.approx(50e-6)
        assert ag["hidden_seconds"] == 0.0
        assert ag["achieved_overlap"] == 0.0

    def test_totals_and_overall_overlap(self, fixture_summary):
        s = fixture_summary
        assert s["compute_seconds"] == pytest.approx(250e-6)
        assert s["collective_seconds"] == pytest.approx(180e-6)
        assert s["hidden_collective_seconds"] == pytest.approx(60e-6)
        assert s["exposed_collective_seconds"] == pytest.approx(120e-6)
        assert s["achieved_overlap"] == pytest.approx(1 / 3, abs=1e-5)
        assert s["total_device_seconds"] == pytest.approx(430e-6)

    def test_top_ops_table(self, fixture_summary):
        top = fixture_summary["top_ops"]
        assert top[0]["op"] == "dot" and top[0]["count"] == 2
        assert top[0]["total_seconds"] == pytest.approx(150e-6)
        assert top[0]["class"] == "compute"
        assert top[0]["share"] == pytest.approx(150 / 430, abs=1e-5)
        by_op = {o["op"]: o for o in top}
        assert by_op["all-reduce"]["class"] == "all-reduce"
        # async -start halves keep their name but classify by kind
        assert by_op["all-gather-start"]["class"] == "all-gather"

    def test_per_step_attribution(self, fixture_summary):
        steps = fixture_summary["steps"]
        assert set(steps) == {"0", "1"}
        s0, s1 = steps["0"], steps["1"]
        assert s0["compute_seconds"] == pytest.approx(250e-6)
        assert s0["collective_seconds"] == pytest.approx(80e-6)
        assert s0["device_seconds"] == pytest.approx(330e-6)
        # step 1 holds the all-reduce tail [200,250) + the whole all-gather
        assert s1["compute_seconds"] == 0.0
        assert s1["collective_seconds"] == pytest.approx(100e-6)

    def test_no_collectives_means_null_overlap(self):
        evs = [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 10,
             "name": "dot.1"},
        ]
        s = ta.analyze_events(evs)
        assert s["achieved_overlap"] is None
        assert s["collective_seconds"] == 0.0

    def test_load_from_gz_and_directory(self, tmp_path, fixture_summary):
        # the capture-dir layout jax.profiler writes, gzipped
        d = tmp_path / "plugins" / "profile" / "2026_01_01"
        d.mkdir(parents=True)
        with gzip.open(d / "host.trace.json.gz", "wt") as f:
            f.write(FIXTURE.read_text())
        s = ta.analyze_trace_dir(tmp_path)
        assert s["num_op_events"] == fixture_summary["num_op_events"]
        assert s["achieved_overlap"] == fixture_summary["achieved_overlap"]

    def test_empty_dir_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            ta.load_trace_events(tmp_path)


# ---------------------------------------------------------------------------
# the guarded global profiler session (double-stop hazard regression)
# ---------------------------------------------------------------------------


class _FakeProfiler:
    """Counts start/stop calls and raises on a stop without a live trace —
    exactly jax.profiler's behavior, minus the profiler."""

    def __init__(self):
        self.starts = 0
        self.stops = 0
        self.active = False

    def start_trace(self, log_dir):
        if self.active:
            raise RuntimeError("profiler already started")
        self.active = True
        self.starts += 1

    def stop_trace(self):
        if not self.active:
            raise RuntimeError("No profiler session active")
        self.active = False
        self.stops += 1


@pytest.fixture()
def fake_profiler(monkeypatch):
    import jax

    fake = _FakeProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)
    return fake


class TestSessionGuard:
    def test_start_stop_round_trip(self, tmp_path, fake_profiler):
        assert trace_mod.start_session(str(tmp_path), "a")
        assert trace_mod.session_owner() == "a"
        assert trace_mod.stop_session("a")
        assert trace_mod.session_owner() is None
        assert fake_profiler.starts == 1 and fake_profiler.stops == 1

    def test_second_owner_refused_not_raised(self, tmp_path, fake_profiler):
        assert trace_mod.start_session(str(tmp_path), "a")
        assert not trace_mod.start_session(str(tmp_path), "b")
        assert fake_profiler.starts == 1  # jax never saw the second start

    def test_stop_by_non_owner_is_noop(self, tmp_path, fake_profiler):
        assert trace_mod.start_session(str(tmp_path), "a")
        assert not trace_mod.stop_session("b")
        assert fake_profiler.stops == 0
        assert trace_mod.stop_session("a")

    def test_double_stop_never_raises(self, tmp_path, fake_profiler):
        assert trace_mod.start_session(str(tmp_path), "a")
        assert trace_mod.stop_session("a")
        assert not trace_mod.stop_session("a")  # the old teardown crash
        assert fake_profiler.stops == 1

    def test_out_of_band_stop_swallowed(self, tmp_path, fake_profiler):
        import jax

        assert trace_mod.start_session(str(tmp_path), "a")
        jax.profiler.stop_trace()  # someone else closed the global session
        assert not trace_mod.stop_session("a")  # logged, not raised


class TestExpManagerProfileGuard:
    def _exp(self, tmp_path, **kw):
        from neuronx_distributed_training_tpu.trainer.exp_manager import (
            ExpManager,
        )

        return ExpManager(exp_dir=str(tmp_path), log_files=False,
                          create_tensorboard_logger=False, **kw)

    def test_teardown_after_closed_window_does_not_double_stop(
            self, tmp_path, fake_profiler):
        """The regression: the profile window's stop at window end vs the
        teardown stop in close() — close() after a closed window must be a
        no-op, not a second stop_trace (which raises)."""
        exp = self._exp(tmp_path, profile_start_step=1, profile_num_steps=1)
        exp.maybe_profile(1)   # window opens
        assert fake_profiler.starts == 1
        exp.maybe_profile(2)   # window closes
        assert fake_profiler.stops == 1
        exp.close()            # must not stop again (and must not raise)
        assert fake_profiler.stops == 1

    def test_teardown_closes_a_still_open_window_once(self, tmp_path,
                                                      fake_profiler):
        exp = self._exp(tmp_path, profile_start_step=1, profile_num_steps=5)
        exp.maybe_profile(1)
        exp.close()
        assert fake_profiler.stops == 1
        exp.close()  # idempotent
        assert fake_profiler.stops == 1

    def test_profile_window_yields_to_live_trace_capture(self, tmp_path,
                                                         fake_profiler):
        # jax allows one global session: a trace capture holding it must
        # make the legacy profile window skip, not crash
        trace_mod.start_session(str(tmp_path / "t"), "telemetry.trace")
        exp = self._exp(tmp_path, profile_start_step=1, profile_num_steps=1)
        exp.maybe_profile(1)
        assert fake_profiler.starts == 1  # only the capture's
        exp.close()
        assert fake_profiler.stops == 0   # capture still owns the session


class TestTraceCaptureWindow:
    def _capture(self, tmp_path, monkeypatch, **cfg_kw):
        from neuronx_distributed_training_tpu.telemetry.trace import (
            TraceCapture,
        )

        def fake_start(log_dir, owner):
            # stand in for jax: "capture" by materializing the fixture
            d = Path(log_dir) / "plugins" / "profile" / "t0"
            d.mkdir(parents=True, exist_ok=True)
            shutil.copy(FIXTURE, d / "host.trace.json")
            return True

        monkeypatch.setattr(trace_mod, "start_session", fake_start)
        monkeypatch.setattr(trace_mod, "stop_session", lambda owner: True)
        return TraceCapture(TraceConfig(enabled=True, **cfg_kw), tmp_path)

    def test_window_produces_summary_and_cleans_raw(self, tmp_path,
                                                    monkeypatch):
        cap = self._capture(tmp_path, monkeypatch, start_step=2, num_steps=2)
        assert cap.maybe_update(0) is None
        assert cap.maybe_update(2) is None and cap.active
        assert cap.maybe_update(3) is None and cap.active
        summary = cap.maybe_update(4)
        assert summary is not None and cap.done
        assert summary["achieved_overlap"] == pytest.approx(1 / 3, abs=1e-5)
        assert summary["window"] == {"start_step": 2, "num_steps": 2}
        on_disk = json.loads((tmp_path / "trace_summary.json").read_text())
        assert on_disk["achieved_overlap"] == summary["achieved_overlap"]
        assert not (tmp_path / "trace").exists()  # keep_raw=False default
        assert cap.maybe_update(5) is None  # one window only

    def test_keep_raw(self, tmp_path, monkeypatch):
        cap = self._capture(tmp_path, monkeypatch, start_step=0, num_steps=1,
                            keep_raw=True)
        cap.maybe_update(0)
        assert cap.maybe_update(1) is not None
        assert (tmp_path / "trace").exists()

    def test_close_inside_window_analyzes(self, tmp_path, monkeypatch):
        cap = self._capture(tmp_path, monkeypatch, start_step=0, num_steps=100)
        cap.maybe_update(0)
        summary = cap.close()
        assert summary is not None
        assert (tmp_path / "trace_summary.json").exists()
        assert cap.close() is None  # idempotent

    def test_disabled_is_inert(self, tmp_path):
        from neuronx_distributed_training_tpu.telemetry.trace import (
            TraceCapture,
        )

        cap = TraceCapture(TraceConfig(enabled=False), tmp_path)
        assert cap.maybe_update(1) is None and not cap.active
        assert cap.close() is None

    def test_busy_session_retries_within_window(self, tmp_path, monkeypatch):
        """A refused session (e.g. a legacy profile window still holds the
        global profiler) must retry at the next in-window step, not abandon
        the whole window."""
        from neuronx_distributed_training_tpu.telemetry.trace import (
            TraceCapture,
        )

        busy = {"until": 3}

        def fake_start(log_dir, owner):
            if busy["until"] > 0:
                busy["until"] -= 1
                return False
            d = Path(log_dir) / "plugins" / "profile" / "t0"
            d.mkdir(parents=True, exist_ok=True)
            shutil.copy(FIXTURE, d / "host.trace.json")
            return True

        monkeypatch.setattr(trace_mod, "start_session", fake_start)
        monkeypatch.setattr(trace_mod, "stop_session", lambda owner: True)
        cap = TraceCapture(TraceConfig(enabled=True, start_step=1,
                                       num_steps=2), tmp_path)
        busy["until"] = 1
        assert cap.maybe_update(1) is None and not cap.active  # refused
        assert cap.maybe_update(2) is None and cap.active      # retried, won
        assert cap.maybe_update(3) is not None                 # window closed

    def test_window_fully_missed_gives_up_once(self, tmp_path, monkeypatch):
        from neuronx_distributed_training_tpu.telemetry.trace import (
            TraceCapture,
        )

        calls = {"n": 0}

        def always_busy(log_dir, owner):
            calls["n"] += 1
            return False

        monkeypatch.setattr(trace_mod, "start_session", always_busy)
        cap = TraceCapture(TraceConfig(enabled=True, start_step=1,
                                       num_steps=2), tmp_path)
        for step in range(6):
            assert cap.maybe_update(step) is None
        assert cap.done and calls["n"] == 2  # one try per in-window step


# ---------------------------------------------------------------------------
# measured-overlap calibration of the autotune cost model
# ---------------------------------------------------------------------------


def _facts(chips_cfg=None):
    from neuronx_distributed_training_tpu.autotune import ModelFacts
    from neuronx_distributed_training_tpu.config.loader import load_config

    cfg = {
        "name": "cal", "model_source": "hf",
        "distributed_strategy": {"tensor_model_parallel_size": 2,
                                 "zero1": True},
        "data": {"seq_length": 2048, "global_batch_size": 64,
                 "micro_batch_size": 1},
        "model": {"architecture": "llama", "vocab_size": 32000,
                  "hidden_size": 2048, "intermediate_size": 5504,
                  "num_layers": 16, "num_attention_heads": 16,
                  "num_key_value_heads": 8,
                  "max_position_embeddings": 2048},
        "precision": {"type": "mixed_precision"},
    }
    cfg.update(chips_cfg or {})
    return ModelFacts.from_config(load_config(cfg)), cfg


class TestOverlapCalibration:
    def test_no_hardcoded_constant_left(self):
        from neuronx_distributed_training_tpu.autotune import cost_model

        assert not hasattr(cost_model, "_COMMS_OVERLAP")

    def test_resolve_overlap_forms(self):
        from neuronx_distributed_training_tpu.autotune import resolve_overlap
        from neuronx_distributed_training_tpu.autotune.topology import (
            TOPOLOGIES,
        )

        topo = TOPOLOGIES["v5e"]
        assert resolve_overlap(None, topo)["default"] == topo.comms_overlap
        assert resolve_overlap(0.8, topo)["tp"] == 0.8
        got = resolve_overlap({"tp": 0.7, "default": 0.2}, topo)
        assert got["tp"] == 0.7 and got["dp"] == 0.2 and got["pp"] == 0.2
        # a measured 1.0 must not price comms as free
        assert resolve_overlap(1.0, topo)["tp"] == 0.99

    def test_topology_table_carries_per_generation_defaults(self):
        from neuronx_distributed_training_tpu.autotune.topology import (
            TOPOLOGIES,
        )

        overlaps = {t.comms_overlap for t in TOPOLOGIES.values()}
        assert len(overlaps) > 1  # a table, not one constant in disguise
        assert all(0.0 < v < 1.0 for v in overlaps)

    def test_estimate_plan_prices_overlap(self):
        from neuronx_distributed_training_tpu.autotune import estimate_plan
        from neuronx_distributed_training_tpu.autotune.topology import (
            TOPOLOGIES,
        )

        facts, _ = _facts()
        plan = facts.declared_plan_for(8)
        topo = TOPOLOGIES["v5e"]
        lo = estimate_plan(facts, plan, topo, overlap=0.1)
        hi = estimate_plan(facts, plan, topo, overlap=0.9)
        assert lo.comms_seconds > hi.comms_seconds > 0
        # exposed time scales with (1 - overlap)
        assert lo.comms_seconds == pytest.approx(
            hi.comms_seconds * (1 - 0.1) / (1 - 0.9), rel=1e-6)
        # default pricing == the topology table's prior
        assert estimate_plan(facts, plan, topo).comms_seconds == (
            pytest.approx(estimate_plan(
                facts, plan, topo, overlap=topo.comms_overlap).comms_seconds))

    def test_calibration_shifts_the_ranking(self):
        """The acceptance bar: a changed measured overlap must be able to
        REORDER plans, not just rescale them — pp-heavy meshes (cheap hops,
        bubble-bound) win when little hiding is measured; wide-tp meshes win
        when the scheduler hides most of the wire time."""
        from neuronx_distributed_training_tpu.autotune import rank_plans
        from neuronx_distributed_training_tpu.autotune.topology import (
            TOPOLOGIES,
        )

        facts, _ = _facts()
        topo = TOPOLOGIES["v5e"]
        lo, _, _ = rank_plans(facts, 16, topo, overlap=0.05)
        hi, _, _ = rank_plans(facts, 16, topo, overlap=0.95)
        assert lo[0].plan.mesh != hi[0].plan.mesh
        assert lo[0].plan.pp > 1       # exposed comms -> pipeline hops win
        assert hi[0].plan.pp == 1      # hidden comms -> flat wide mesh wins

    def test_overlap_from_trace_summary(self, fixture_summary):
        from neuronx_distributed_training_tpu.autotune import (
            overlap_from_trace_summary,
        )

        got = overlap_from_trace_summary(fixture_summary)
        assert got["default"] == pytest.approx(1 / 3, abs=1e-5)
        # tp/dp take the wire-weighted AG+RS+AR overlap: (0 + 60)/(50 + 130)
        assert got["tp"] == pytest.approx(60 / 180, abs=1e-6)
        assert got["dp"] == pytest.approx(60 / 180, abs=1e-6)
        # classes absent from the trace fall back to default at resolve time
        assert "pp" not in got and "ep" not in got

    def test_overlap_from_summary_requires_collectives(self):
        from neuronx_distributed_training_tpu.autotune import (
            overlap_from_trace_summary,
        )

        with pytest.raises(ValueError, match="calibrate"):
            overlap_from_trace_summary({"overlap_by_class": {}})

    def test_malformed_class_entry_is_valueerror_not_crash(self, tmp_path):
        # a hand-edited/schema-drifted summary must become a report error
        # (plan_config catches ValueError), never a CLI traceback
        from neuronx_distributed_training_tpu.autotune import (
            overlap_from_trace_summary,
            plan_config,
        )

        bad = {"achieved_overlap": 0.5,
               "overlap_by_class": {"all-gather": 0.7}}
        with pytest.raises(ValueError, match="overlap_by_class"):
            overlap_from_trace_summary(bad)
        _, cfg = _facts()
        p = tmp_path / "trace_summary.json"
        p.write_text(json.dumps(bad))
        rep = plan_config(cfg, chips=8, topology="v5e", audit=False,
                          calibration=str(p))
        assert rep.error and "calibration" in rep.error

    def test_plan_config_calibration_path(self, tmp_path, fixture_summary):
        from neuronx_distributed_training_tpu.autotune import plan_config

        _, cfg = _facts()
        p = tmp_path / "trace_summary.json"
        p.write_text(json.dumps(fixture_summary))
        rep = plan_config(cfg, chips=8, topology="v5e", audit=False,
                          top_k=3, calibration=str(p))
        assert rep.error is None
        assert rep.overlap["measured"] is True
        assert rep.overlap["tp"] == pytest.approx(60 / 180, abs=1e-4)
        assert "overlap" in rep.to_dict()
        # un-calibrated: the topology prior, marked as such
        rep2 = plan_config(cfg, chips=8, topology="v5e", audit=False,
                           top_k=3)
        assert rep2.overlap["measured"] is False
        assert rep2.overlap["tp"] == pytest.approx(0.5)

    def test_plan_config_bad_calibration_is_report_error(self, tmp_path):
        from neuronx_distributed_training_tpu.autotune import plan_config

        _, cfg = _facts()
        rep = plan_config(cfg, chips=8, topology="v5e", audit=False,
                          calibration=str(tmp_path / "nope.json"))
        assert rep.error and "calibration" in rep.error


# ---------------------------------------------------------------------------
# live CPU-captured trace through real tiny-llama fit()
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def traced_run(tmp_path_factory, devices8):
    """One tiny fit() with a real telemetry.trace window on the CPU backend;
    shared across the smoke assertions."""
    from neuronx_distributed_training_tpu.config.loader import load_config
    from neuronx_distributed_training_tpu.trainer.loop import Trainer

    tmp_path = tmp_path_factory.mktemp("traced_run")
    cfg = load_config({
        "name": "tr", "model_source": "hf", "seed": 7,
        "trainer": {"max_steps": 4, "log_every_n_steps": 1},
        "exp_manager": {"exp_dir": str(tmp_path / "exp"),
                        "create_tensorboard_logger": False,
                        "log_files": False,
                        "telemetry": {"trace": {"enabled": True,
                                                "start_step": 1,
                                                "num_steps": 2}}},
        "distributed_strategy": {"tensor_model_parallel_size": 2,
                                 "sequence_parallel": True},
        "data": {"global_batch_size": 8, "micro_batch_size": 1,
                 "seq_length": 32, "synthetic": True},
        "model": {"vocab_size": 128, "hidden_size": 64,
                  "intermediate_size": 128, "num_layers": 2,
                  "num_attention_heads": 4, "num_key_value_heads": 2,
                  "max_position_embeddings": 32,
                  "optim": {"name": "adamw_fp32OptState", "lr": 1e-3}},
        "precision": {"type": "mixed_precision"},
    })
    t = Trainer.from_config(cfg, enable_checkpointing=False)
    metrics = t.fit()
    exp_dir = tmp_path / "exp" / "tr" / "version_0"
    summary = json.loads((exp_dir / "trace_summary.json").read_text())
    run_summary = json.loads((exp_dir / "run_summary.json").read_text())
    return t, metrics, summary, run_summary, exp_dir


class TestLiveTraceSmoke:
    def test_summary_written_with_real_collectives(self, traced_run):
        _, metrics, summary, _, _ = traced_run
        import numpy as np

        assert np.isfinite(metrics["loss"])
        # tp=2 + SP inserts real collectives; the CPU backend traces them
        assert summary["num_op_events"] > 0
        assert summary["collective_seconds"] > 0
        assert summary["overlap_by_class"], summary.keys()
        assert 0.0 <= summary["achieved_overlap"] <= 1.0
        for c in summary["overlap_by_class"].values():
            assert c["wire_seconds"] == pytest.approx(
                c["hidden_seconds"] + c["exposed_seconds"], rel=1e-6)

    def test_top_ops_and_window_steps(self, traced_run):
        _, _, summary, _, _ = traced_run
        assert summary["top_ops"] and summary["top_ops"][0]["total_seconds"] > 0
        # per-step attribution covers exactly the traced window [1, 3)
        assert set(summary["steps"]) <= {"1", "2"}
        assert summary["steps"], "no StepTraceAnnotation windows captured"
        assert summary["window"] == {"start_step": 1, "num_steps": 2}

    def test_raw_artifacts_cleaned_up(self, traced_run):
        *_, exp_dir = traced_run
        assert not (exp_dir / "trace").exists()  # keep_raw defaults off

    def test_run_summary_carries_trace_section(self, traced_run):
        _, _, summary, run_summary, _ = traced_run
        tr = run_summary["trace"]
        assert tr["achieved_overlap"] == summary["achieved_overlap"]
        assert tr["exposed_collective_seconds"] == (
            summary["exposed_collective_seconds"])
        assert tr["summary_path"].endswith("trace_summary.json")

    def test_comms_section_joins_live_wire_times(self, traced_run):
        # the interconnect observatory's in-loop layer: the cost model's
        # per-class byte volumes joined with the traced wire seconds into
        # achieved bus bandwidth + efficiency vs the topology peak
        from neuronx_distributed_training_tpu.telemetry.comms import (
            comms_metrics,
        )

        _, _, summary, run_summary, _ = traced_run
        section = summary.get("comms")
        assert section, "trace summary carries no comms section"
        assert section["window_steps"] == 2
        assert section["topology"] == "cpu"
        assert section["peak_bandwidth_gbps"] > 0
        for kind, e in section["classes"].items():
            assert kind in summary["overlap_by_class"]
            assert e["achieved_gbps"] > 0
            assert e["bus_bytes_per_step"] > 0
            assert e["wire_seconds_per_step"] > 0
            assert e["efficiency"] > 0
            assert e["count"] > 0
        # run_summary mirrors the section at the TOP level (where the perf
        # contract's run-dir extraction and tools/comms_report.py read it),
        # and the flattened scalars rode the metric stream to every sink
        assert run_summary["comms"] == section
        scalars = comms_metrics(section)
        kind = sorted(section["classes"])[0]
        assert f"comms/{kind}/achieved_gbps" in scalars
        assert f"comms/{kind}/efficiency" in scalars

    def test_calibrates_the_planner_end_to_end(self, traced_run):
        # the full loop: captured trace -> measured overlap -> plan pricing
        from neuronx_distributed_training_tpu.autotune import plan_config

        *_, exp_dir = traced_run
        _, cfg = _facts()
        rep = plan_config(cfg, chips=8, topology="v5e", audit=False,
                          top_k=2, calibration=str(exp_dir))
        assert rep.error is None and rep.overlap["measured"] is True


# ---------------------------------------------------------------------------
# tools/trace_report.py + metrics_report trace section
# ---------------------------------------------------------------------------


def _load_tool(name):
    path = Path(__file__).resolve().parents[1] / "tools" / f"{name}.py"
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestTraceReportCLI:
    def test_renders_summary_file(self, tmp_path, fixture_summary, capsys):
        tr = _load_tool("trace_report")
        p = tmp_path / "trace_summary.json"
        p.write_text(json.dumps(fixture_summary))
        assert tr.main([str(p)]) == 0
        out = capsys.readouterr().out
        for needle in ("achieved_overlap", "all-reduce", "all-gather",
                       "top", "step 0", "hidden", "exposed",
                       "--calibrate-from"):
            assert needle in out, (needle, out)

    def test_renders_run_dir_and_json_contract(self, tmp_path,
                                               fixture_summary, capsys):
        tr = _load_tool("trace_report")
        (tmp_path / "trace_summary.json").write_text(
            json.dumps(fixture_summary))
        assert tr.main([str(tmp_path), "--json", "-"]) == 0
        out = capsys.readouterr().out
        payload = json.loads(out.strip().splitlines()[-1])  # last line = JSON
        assert payload["achieved_overlap"] == pytest.approx(1 / 3, abs=1e-5)

    def test_parses_raw_trace_file(self, tmp_path, capsys):
        tr = _load_tool("trace_report")
        assert tr.main([str(FIXTURE)]) == 0
        assert "achieved_overlap" in capsys.readouterr().out

    def test_missing_path_errors(self, tmp_path):
        tr = _load_tool("trace_report")
        assert tr.main([str(tmp_path / "nope.json")]) == 2

    def test_renders_real_run_output(self, traced_run, capsys):
        tr = _load_tool("trace_report")
        *_, exp_dir = traced_run
        assert tr.main([str(exp_dir)]) == 0
        assert "achieved_overlap" in capsys.readouterr().out


class TestMetricsReportTraceSection:
    def test_trace_summary_rendered_when_present(self, tmp_path,
                                                 fixture_summary, capsys):
        mr = _load_tool("metrics_report")
        with open(tmp_path / "metrics.jsonl", "w") as f:
            f.write(json.dumps({"step": 1, "loss": 5.0}) + "\n")
        (tmp_path / "run_summary.json").write_text(
            json.dumps({"compile_seconds": 1.0}))
        (tmp_path / "trace_summary.json").write_text(
            json.dumps(fixture_summary))
        assert mr.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        for needle in ("device-time trace", "achieved_overlap",
                       "trace_report.py", "dot"):
            assert needle in out, (needle, out)

    def test_absent_trace_summary_is_silent(self, tmp_path, capsys):
        mr = _load_tool("metrics_report")
        with open(tmp_path / "metrics.jsonl", "w") as f:
            f.write(json.dumps({"step": 1, "loss": 5.0}) + "\n")
        assert mr.main([str(tmp_path)]) == 0
        assert "device-time trace" not in capsys.readouterr().out
