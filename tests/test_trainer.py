"""Trainer loop: golden short-run (the reference's TRAIN_ITERS pattern),
checkpoint-resume exactness, exp-manager logging."""

import json

import numpy as np
import pytest

from neuronx_distributed_training_tpu.config.loader import load_config
from neuronx_distributed_training_tpu.trainer.loop import Trainer, train


def tiny_cfg(tmp_path, max_steps=5, **over):
    cfg = {
        "name": "tiny",
        "model_source": "hf",
        "seed": 7,
        "trainer": {"max_steps": max_steps, "log_every_n_steps": 1},
        "exp_manager": {
            "exp_dir": str(tmp_path / "exp"),
            "resume_if_exists": True,
            "checkpoint_callback_params": {"save_top_k": 2, "every_n_train_steps": 2},
        },
        "distributed_strategy": {"tensor_model_parallel_size": 2, "sequence_parallel": True},
        "data": {"global_batch_size": 8, "micro_batch_size": 1, "seq_length": 32},
        "model": {
            "vocab_size": 128,
            "hidden_size": 64,
            "intermediate_size": 128,
            "num_layers": 2,
            "num_attention_heads": 4,
            "num_key_value_heads": 2,
            "max_position_embeddings": 32,
            "optim": {
                "name": "adamw_fp32OptState",
                "lr": 1e-3,
                "sched": {"name": "LinearAnnealingWithWarmUp", "warmup_steps": 2,
                          "max_steps": max_steps},
            },
        },
        "precision": {"type": "mixed_precision"},
    }
    cfg.update(over)
    return load_config(cfg)


class TestFit:
    def test_short_run_loss_finite_and_logged(self, tmp_path, devices8):
        cfg = tiny_cfg(tmp_path)
        metrics = train(cfg)
        assert np.isfinite(metrics["loss"])
        assert metrics["grad_norm"] > 0
        assert metrics["consumed_samples"] == 40  # 5 steps x gbs 8
        # metrics.jsonl written every step
        exp_dir = tmp_path / "exp" / "tiny" / "version_0"
        lines = (exp_dir / "metrics.jsonl").read_text().strip().splitlines()
        assert len(lines) == 5
        rec = json.loads(lines[-1])
        assert rec["step"] == 5 and "lr" in rec and "loss" in rec

    def test_resume_continues_exactly(self, tmp_path, devices8):
        cfg = tiny_cfg(tmp_path, max_steps=4)
        t1 = Trainer.from_config(cfg)
        t1.fit()  # saves at steps 2, 4
        # "crash" and restart with a longer horizon: must resume from step 4
        cfg2 = tiny_cfg(tmp_path, max_steps=6)
        t2 = Trainer.from_config(cfg2)
        assert t2.maybe_resume()
        assert t2.step == 4
        assert t2.data_module.consumed_samples == 32
        m = t2.fit()
        assert m["consumed_samples"] == 48

    def test_resume_bitwise_params(self, tmp_path, devices8):
        """A run that checkpoints at step 2 and resumes to step 4 must match an
        uninterrupted 4-step run bit-for-bit (same data order, same RNG)."""
        cfg_a = tiny_cfg(tmp_path, max_steps=4,
                         exp_manager={"exp_dir": str(tmp_path / "exp_a"),
                                      "resume_if_exists": True,
                                      "checkpoint_callback_params":
                                          {"save_top_k": 1, "every_n_train_steps": 2}})
        straight = Trainer.from_config(cfg_a)
        straight.fit()
        w_straight = np.asarray(
            straight.params["layers"]["attn"]["qkv"]["w"]
        )

        cfg_b = tiny_cfg(tmp_path, max_steps=2,
                         exp_manager={"exp_dir": str(tmp_path / "exp_b"),
                                      "resume_if_exists": True,
                                      "checkpoint_callback_params":
                                          {"save_top_k": 1, "every_n_train_steps": 2}})
        first = Trainer.from_config(cfg_b)
        first.fit()
        cfg_b2 = tiny_cfg(tmp_path, max_steps=4,
                          exp_manager={"exp_dir": str(tmp_path / "exp_b"),
                                       "resume_if_exists": True,
                                       "checkpoint_callback_params":
                                           {"save_top_k": 1, "every_n_train_steps": 2}})
        second = Trainer.from_config(cfg_b2)
        second.fit()
        w_resumed = np.asarray(second.params["layers"]["attn"]["qkv"]["w"])
        np.testing.assert_array_equal(w_straight, w_resumed)

    def test_validation_loop(self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.data import SyntheticDataModule

        cfg = tiny_cfg(tmp_path, max_steps=2,
                       trainer={"max_steps": 2, "log_every_n_steps": 1,
                                "val_check_interval": 2, "limit_val_batches": 2})
        val_dm = SyntheticDataModule(vocab_size=128, seq_len=32, global_batch_size=8, seed=99)
        t = Trainer.from_config(cfg, val_data_module=val_dm)
        m = t.fit()
        assert np.isfinite(m["val_loss"])


class TestBuildModel:
    def test_unknown_arch_raises(self, tmp_path):
        from neuronx_distributed_training_tpu.trainer.loop import build_model
        from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

        cfg = tiny_cfg(tmp_path)
        cfg["model"]["architecture"] = "rwkv"
        with pytest.raises(ValueError, match="unsupported"):
            build_model(cfg, DtypePolicy())
