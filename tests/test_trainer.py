"""Trainer loop: golden short-run (the reference's TRAIN_ITERS pattern),
checkpoint-resume exactness, exp-manager logging."""

import json

import numpy as np
import pytest

from neuronx_distributed_training_tpu.config.loader import load_config
from neuronx_distributed_training_tpu.trainer.loop import Trainer, train

import pytest as _pytest_mark

pytestmark = _pytest_mark.mark.slow  # fit()-based integration tests; CI fast tier deselects


def tiny_cfg(tmp_path, max_steps=5, **over):
    cfg = {
        "name": "tiny",
        "model_source": "hf",
        "seed": 7,
        "trainer": {"max_steps": max_steps, "log_every_n_steps": 1},
        "exp_manager": {
            "exp_dir": str(tmp_path / "exp"),
            "resume_if_exists": True,
            "checkpoint_callback_params": {"save_top_k": 2, "every_n_train_steps": 2},
        },
        "distributed_strategy": {"tensor_model_parallel_size": 2, "sequence_parallel": True},
        "data": {"global_batch_size": 8, "micro_batch_size": 1, "seq_length": 32,
                 "synthetic": True},
        "model": {
            "vocab_size": 128,
            "hidden_size": 64,
            "intermediate_size": 128,
            "num_layers": 2,
            "num_attention_heads": 4,
            "num_key_value_heads": 2,
            "max_position_embeddings": 32,
            "optim": {
                "name": "adamw_fp32OptState",
                "lr": 1e-3,
                "sched": {"name": "LinearAnnealingWithWarmUp", "warmup_steps": 2,
                          "max_steps": max_steps},
            },
        },
        "precision": {"type": "mixed_precision"},
    }
    cfg.update(over)
    return load_config(cfg)


class TestFit:
    def test_short_run_loss_finite_and_logged(self, tmp_path, devices8):
        cfg = tiny_cfg(tmp_path)
        metrics = train(cfg)
        assert np.isfinite(metrics["loss"])
        assert metrics["grad_norm"] > 0
        assert metrics["consumed_samples"] == 40  # 5 steps x gbs 8
        # metrics.jsonl written every step
        exp_dir = tmp_path / "exp" / "tiny" / "version_0"
        lines = (exp_dir / "metrics.jsonl").read_text().strip().splitlines()
        assert len(lines) == 5
        rec = json.loads(lines[-1])
        assert rec["step"] == 5 and "lr" in rec and "loss" in rec

    def test_resume_continues_exactly(self, tmp_path, devices8):
        cfg = tiny_cfg(tmp_path, max_steps=4)
        t1 = Trainer.from_config(cfg)
        t1.fit()  # saves at steps 2, 4
        # "crash" and restart with a longer horizon: must resume from step 4
        cfg2 = tiny_cfg(tmp_path, max_steps=6)
        t2 = Trainer.from_config(cfg2)
        assert t2.maybe_resume()
        assert t2.step == 4
        assert t2.data_module.consumed_samples == 32
        m = t2.fit()
        assert m["consumed_samples"] == 48

    def test_resume_bitwise_params(self, tmp_path, devices8):
        """A run that checkpoints at step 2 and resumes to step 4 must match an
        uninterrupted 4-step run bit-for-bit (same data order, same RNG)."""
        cfg_a = tiny_cfg(tmp_path, max_steps=4,
                         exp_manager={"exp_dir": str(tmp_path / "exp_a"),
                                      "resume_if_exists": True,
                                      "checkpoint_callback_params":
                                          {"save_top_k": 1, "every_n_train_steps": 2}})
        straight = Trainer.from_config(cfg_a)
        straight.fit()
        w_straight = np.asarray(
            straight.params["layers"]["attn"]["qkv"]["w"]
        )

        cfg_b = tiny_cfg(tmp_path, max_steps=2,
                         exp_manager={"exp_dir": str(tmp_path / "exp_b"),
                                      "resume_if_exists": True,
                                      "checkpoint_callback_params":
                                          {"save_top_k": 1, "every_n_train_steps": 2}})
        first = Trainer.from_config(cfg_b)
        first.fit()
        cfg_b2 = tiny_cfg(tmp_path, max_steps=4,
                          exp_manager={"exp_dir": str(tmp_path / "exp_b"),
                                       "resume_if_exists": True,
                                       "checkpoint_callback_params":
                                           {"save_top_k": 1, "every_n_train_steps": 2}})
        second = Trainer.from_config(cfg_b2)
        second.fit()
        w_resumed = np.asarray(second.params["layers"]["attn"]["qkv"]["w"])
        np.testing.assert_array_equal(w_straight, w_resumed)

    def test_validation_loop(self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.data import SyntheticDataModule

        cfg = tiny_cfg(tmp_path, max_steps=2,
                       trainer={"max_steps": 2, "log_every_n_steps": 1,
                                "val_check_interval": 2, "limit_val_batches": 2})
        val_dm = SyntheticDataModule(vocab_size=128, seq_len=32, global_batch_size=8, seed=99)
        t = Trainer.from_config(cfg, val_data_module=val_dm)
        m = t.fit()
        assert np.isfinite(m["val_loss"])


class TestBuildModel:
    def test_unknown_arch_raises(self, tmp_path):
        from neuronx_distributed_training_tpu.trainer.loop import build_model
        from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

        cfg = tiny_cfg(tmp_path)
        cfg["model"]["architecture"] = "rwkv"
        with pytest.raises(ValueError, match="unsupported"):
            build_model(cfg, DtypePolicy())


def test_pipeline_vpp_trainer(tmp_path, devices8):
    """Trainer wiring for pp=2 x vp=2: loss finite, steps run, resume-safe specs."""
    cfg = tiny_cfg(tmp_path, max_steps=2)
    cfg["distributed_strategy"] = {
        "pipeline_model_parallel_size": 2,
        "virtual_pipeline_model_parallel_size": 2,
        "tensor_model_parallel_size": 2,
        "sequence_parallel": True,
        "zero1": True,
    }
    cfg["model"]["num_layers"] = 4  # divisible by pp*vp
    cfg["data"]["micro_batch_size"] = 1
    from neuronx_distributed_training_tpu.config.loader import load_config

    cfg = load_config(dict(cfg))
    t = Trainer.from_config(cfg, enable_checkpointing=False)
    assert t.params["layers"]["attn"]["qkv"]["w"].shape[:2] == (2, 2)  # [vp, pp]
    m = t.fit()
    assert np.isfinite(m["loss"])


def test_lora_trainer_freezes_base(tmp_path, devices8):
    """model.lora config: adapters injected, base weights frozen through fit()."""
    cfg = tiny_cfg(tmp_path, max_steps=2)
    cfg["model"]["lora"] = {"lora_rank": 4, "lora_alpha": 8,
                            "target_modules": ["qkv_proj", "o_proj"]}
    t = Trainer.from_config(cfg, enable_checkpointing=False)
    w_before = np.asarray(t.params["layers"]["attn"]["qkv"]["w"]).copy()
    b_before = np.asarray(t.params["layers"]["attn"]["qkv"]["lora_b"]).copy()
    m = t.fit()
    assert np.isfinite(m["loss"])
    np.testing.assert_array_equal(
        np.asarray(t.params["layers"]["attn"]["qkv"]["w"]), w_before
    )
    assert not np.array_equal(
        np.asarray(t.params["layers"]["attn"]["qkv"]["lora_b"]), b_before
    )


def test_dpo_trainer_end_to_end(tmp_path, devices8):
    """model_alignment_strategy: dpo — pre-fit reference pass + preference loss."""
    from neuronx_distributed_training_tpu.data.modules import DPODataModule

    class CharTok:
        eos_token_id = 1
        def encode(self, s):
            return [3 + (ord(c) % 60) for c in s]

    cfg = tiny_cfg(tmp_path, max_steps=2)
    cfg["model_alignment_strategy"] = "dpo"
    cfg["model"]["dpo"] = {"beta": 0.1}
    cfg["data"]["global_batch_size"] = 8
    records = [{"prompt": f"q{i}", "chosen": "yes good", "rejected": "no"}
               for i in range(16)]
    dm = DPODataModule(records, CharTok(), seq_length=32, global_batch_size=8)
    t = Trainer.from_config(cfg, data_module=dm, enable_checkpointing=False)
    m = t.fit()
    assert np.isfinite(m["loss"])
    # reference columns were attached by the pre-fit pass
    assert "reference_chosen_logps" in dm.arrays
    assert "reward_accuracy" in m or m["loss"] > 0


def test_orpo_trainer_end_to_end(tmp_path, devices8):
    """model_alignment_strategy: orpo — no reference pass, odds-ratio loss."""
    from neuronx_distributed_training_tpu.data.modules import DPODataModule

    class CharTok:
        eos_token_id = 1
        def encode(self, s):
            return [3 + (ord(c) % 60) for c in s]

    cfg = tiny_cfg(tmp_path, max_steps=2)
    cfg["model_alignment_strategy"] = {"orpo": {"kl_beta": 0.2}}
    records = [{"prompt": f"q{i}", "chosen": "yes good", "rejected": "no"}
               for i in range(16)]
    dm = DPODataModule(records, CharTok(), seq_length=32, global_batch_size=8)
    t = Trainer.from_config(cfg, data_module=dm, enable_checkpointing=False)
    assert t.pre_fit is None  # ORPO has no frozen-reference pass
    m = t.fit()
    assert np.isfinite(m["loss"])
    assert "orpo_log_odds" in m
    assert "reference_chosen_logps" not in dm.arrays


def test_ema_weights_tracked_and_evaluated(tmp_path, devices8):
    """exp_manager.ema: EMA tree in opt state, decays toward params, and
    validate() can evaluate with EMA weights instead."""
    from neuronx_distributed_training_tpu.data import SyntheticDataModule

    cfg = tiny_cfg(tmp_path, max_steps=3,
                   trainer={"max_steps": 3, "log_every_n_steps": 1,
                            "val_check_interval": 3, "limit_val_batches": 1})
    cfg["exp_manager"]["ema"] = {"enable": True, "decay": 0.5,
                                 "evaluate_ema_weights_instead": True}
    cfg = load_config(dict(cfg))
    val_dm = SyntheticDataModule(vocab_size=128, seq_len=32, global_batch_size=8, seed=9)
    t = Trainer.from_config(cfg, val_data_module=val_dm, enable_checkpointing=False)
    assert "ema" in t.opt_state
    ema0 = np.asarray(t.opt_state["ema"]["layers"]["attn"]["qkv"]["w"]).copy()
    m = t.fit()
    assert np.isfinite(m["val_loss"])
    ema1 = np.asarray(t.opt_state["ema"]["layers"]["attn"]["qkv"]["w"])
    w1 = np.asarray(t.params["layers"]["attn"]["qkv"]["w"], dtype=np.float32)
    assert not np.array_equal(ema0, ema1)  # EMA moved
    # with decay 0.5 over 3 steps, EMA lags params but tracks them
    assert np.abs(ema1 - w1).max() < np.abs(ema0 - w1).max()


def test_max_time_stops_and_checkpoints(tmp_path, devices8):
    """trainer.max_time: the loop stops early, saves a resumable checkpoint."""
    cfg = tiny_cfg(tmp_path, max_steps=100000)
    cfg["trainer"]["max_time"] = "00:00:00:02"  # 2 seconds
    cfg = load_config(dict(cfg))
    t = Trainer.from_config(cfg)
    m = t.fit()
    assert 0 < t.step < 100000
    assert t.checkpointer is None or True  # checkpointer was closed in fit
    # a resumable checkpoint exists at the stop step
    t2 = Trainer.from_config(load_config(dict(tiny_cfg(tmp_path, max_steps=100000))))
    assert t2.maybe_resume()
    assert t2.step == t.step


def test_parse_max_time():
    from neuronx_distributed_training_tpu.trainer.loop import parse_max_time

    assert parse_max_time(None) is None
    assert parse_max_time("00:01:30:15") == 5415.0
    assert parse_max_time(90) == 90.0
    import pytest as _pytest

    with _pytest.raises(ValueError):
        parse_max_time("1:30")


def test_dpo_mixtral_and_orpo_gpt(tmp_path, devices8):
    """Preference alignment now works for every model family (non-PP)."""
    from neuronx_distributed_training_tpu.data.modules import DPODataModule

    class CharTok:
        eos_token_id = 1
        def encode(self, s):
            return [3 + (ord(c) % 60) for c in s]

    records = [{"prompt": f"q{i}", "chosen": "yes good", "rejected": "no"}
               for i in range(16)]

    # Mixtral + DPO
    cfg = tiny_cfg(tmp_path, max_steps=1)
    cfg["model_alignment_strategy"] = "dpo"
    cfg["model"]["architecture"] = "mixtral"
    cfg["model"]["moe"] = {"num_experts": 2, "top_k": 1, "dropless": True}
    dm = DPODataModule(records, CharTok(), seq_length=32, global_batch_size=8)
    t = Trainer.from_config(cfg, data_module=dm, enable_checkpointing=False)
    m = t.fit()
    assert np.isfinite(m["loss"])
    assert "reference_chosen_logps" in dm.arrays

    # Megatron-GPT + ORPO
    cfg2 = tiny_cfg(tmp_path, max_steps=1,
                    exp_manager={"exp_dir": str(tmp_path / "exp2")})
    cfg2["model_alignment_strategy"] = {"orpo": {"kl_beta": 0.2}}
    cfg2["model_source"] = "megatron"
    cfg2["model"]["architecture"] = "gpt"
    dm2 = DPODataModule(records, CharTok(), seq_length=32, global_batch_size=8)
    t2 = Trainer.from_config(cfg2, data_module=dm2, enable_checkpointing=False)
    m2 = t2.fit()
    assert np.isfinite(m2["loss"])
    assert "orpo_log_odds" in m2


def test_dpo_vpp_trainer(tmp_path, devices8):
    """DPO under the interleaved pipeline: the reference pass de-interleaves
    the layer stack for its plain forward."""
    from neuronx_distributed_training_tpu.data.modules import DPODataModule

    class CharTok:
        eos_token_id = 1
        def encode(self, s):
            return [3 + (ord(c) % 60) for c in s]

    cfg = tiny_cfg(tmp_path, max_steps=1)
    cfg["model_alignment_strategy"] = "dpo"
    cfg["distributed_strategy"] = {
        "pipeline_model_parallel_size": 2,
        "virtual_pipeline_model_parallel_size": 2,
        "tensor_model_parallel_size": 2,
        "sequence_parallel": True,
    }
    cfg["model"]["num_layers"] = 4
    records = [{"prompt": f"q{i}", "chosen": "yes good", "rejected": "no"}
               for i in range(16)]
    dm = DPODataModule(records, CharTok(), seq_length=32, global_batch_size=8)
    t = Trainer.from_config(cfg, data_module=dm, enable_checkpointing=False)
    m = t.fit()
    assert np.isfinite(m["loss"])
    assert "reference_chosen_logps" in dm.arrays


def test_mixtral_pipeline_trainer(tmp_path, devices8):
    """Trainer wiring for mixtral under pp=2 (router aux psum through the
    pipelined loss), incl. moe_frequency=2 grouped stage slicing."""
    for freq in (1, 2):
        cfg = tiny_cfg(tmp_path, max_steps=1,
                       exp_manager={"exp_dir": str(tmp_path / f"exp_f{freq}")})
        cfg["model"]["architecture"] = "mixtral"
        cfg["model"]["num_layers"] = 4
        cfg["model"]["moe"] = {"num_experts": 2, "top_k": 1, "dropless": True,
                               "frequency": freq}
        cfg["distributed_strategy"] = {
            "pipeline_model_parallel_size": 2,
            "tensor_model_parallel_size": 2,
            "sequence_parallel": True,
        }
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        m = t.fit()
        assert np.isfinite(m["loss"]), f"frequency={freq}"


def test_preference_pp_mixtral_and_gpt(tmp_path, devices8):
    """DPO/ORPO under pipeline parallelism for the non-llama families:
    concatenated forward through MoE stages ((x, aux) tuples) with the
    per-family head_fn."""
    from neuronx_distributed_training_tpu.data.modules import DPODataModule

    class CharTok:
        eos_token_id = 1
        def encode(self, s):
            return [3 + (ord(c) % 60) for c in s]

    records = [{"prompt": f"q{i}", "chosen": "yes good", "rejected": "no"}
               for i in range(16)]

    # Mixtral + DPO + pp=2
    cfg = tiny_cfg(tmp_path, max_steps=1)
    cfg["model_alignment_strategy"] = "dpo"
    cfg["model"]["architecture"] = "mixtral"
    cfg["model"]["moe"] = {"num_experts": 2, "top_k": 1, "dropless": True}
    cfg["model"]["num_layers"] = 4
    cfg["distributed_strategy"] = {"pipeline_model_parallel_size": 2}
    dm = DPODataModule(records, CharTok(), seq_length=32, global_batch_size=8)
    t = Trainer.from_config(cfg, data_module=dm, enable_checkpointing=False)
    m = t.fit()
    assert np.isfinite(m["loss"])
    assert "reference_chosen_logps" in dm.arrays

    # Megatron-GPT + ORPO + pp=2
    cfg2 = tiny_cfg(tmp_path, max_steps=1,
                    exp_manager={"exp_dir": str(tmp_path / "exp2")})
    cfg2["model_alignment_strategy"] = {"orpo": {"kl_beta": 0.2}}
    cfg2["model_source"] = "megatron"
    cfg2["model"]["architecture"] = "gpt"
    cfg2["model"]["num_layers"] = 4
    cfg2["distributed_strategy"] = {"pipeline_model_parallel_size": 2}
    dm2 = DPODataModule(records, CharTok(), seq_length=32, global_batch_size=8)
    t2 = Trainer.from_config(cfg2, data_module=dm2, enable_checkpointing=False)
    m2 = t2.fit()
    assert np.isfinite(m2["loss"])


def test_pp_val_batch_size_mismatch_raises(tmp_path, devices8):
    """Under PP, a val module with a different global batch size must fail
    fast with a clear error (not deep inside shard_map)."""
    from neuronx_distributed_training_tpu.data import SyntheticDataModule

    cfg = tiny_cfg(tmp_path, max_steps=1)
    cfg["distributed_strategy"] = {"pipeline_model_parallel_size": 2}
    cfg["model"]["num_layers"] = 4
    val_dm = SyntheticDataModule(vocab_size=128, seq_len=32,
                                 global_batch_size=4, seed=9)
    with pytest.raises(ValueError, match="global_batch_size"):
        Trainer.from_config(cfg, val_data_module=val_dm,
                            enable_checkpointing=False)


def test_warm_start_seeds_master_weights(tmp_path, devices8):
    """weight_init_only warm start under a master-weights regime (bf16SR):
    opt_state['master'] must copy the RESTORED weights, not random init —
    otherwise step 1 derives new params from the random master and silently
    voids the warm start."""
    cfg1 = tiny_cfg(tmp_path, max_steps=2)
    cfg1["precision"] = {"type": "bf16SR"}
    t1 = Trainer.from_config(load_config(dict(cfg1)))
    t1.fit()
    ckpt_dir = tmp_path / "exp" / "tiny" / "version_0" / "checkpoints"
    trained_w = np.asarray(t1.params["layers"]["attn"]["qkv"]["w"],
                           dtype=np.float32)

    cfg2 = tiny_cfg(tmp_path, max_steps=1,
                    exp_manager={"exp_dir": str(tmp_path / "exp2"),
                                 "resume_from_checkpoint": str(ckpt_dir)})
    cfg2["precision"] = {"type": "bf16SR"}
    cfg2["model"]["weight_init_only"] = True
    cfg2["seed"] = 99  # different init — a leaked random master would differ
    t2 = Trainer.from_config(load_config(dict(cfg2)), enable_checkpointing=False)
    restored_w = np.asarray(t2.params["layers"]["attn"]["qkv"]["w"],
                            dtype=np.float32)
    np.testing.assert_allclose(restored_w, trained_w, rtol=0, atol=0)
    assert "master" in t2.opt_state, "bf16SR must carry fp32 master weights"
    master_w = np.asarray(t2.opt_state["master"]["layers"]["attn"]["qkv"]["w"])
    np.testing.assert_allclose(master_w, trained_w, rtol=0, atol=0)


def test_kto_trainer_end_to_end(tmp_path, devices8):
    """model_alignment_strategy: kto — unpaired (prompt, completion, label)
    records; frozen-reference pass attaches reference_logps; one fit() epoch
    produces a finite loss and KTO metrics."""
    from neuronx_distributed_training_tpu.data.modules import KTODataModule

    class CharTok:
        eos_token_id = 1
        def encode(self, s):
            return [3 + (ord(c) % 60) for c in s]

    cfg = tiny_cfg(tmp_path, max_steps=2)
    cfg["model_alignment_strategy"] = {"kto": {"kl_beta": 0.2}}
    records = [{"prompt": f"q{i}", "completion": "yes good" if i % 2 else "no",
                "label": bool(i % 2)} for i in range(16)]
    dm = KTODataModule(records, CharTok(), seq_length=32, global_batch_size=8)
    t = Trainer.from_config(cfg, data_module=dm, enable_checkpointing=False)
    m = t.fit()
    assert np.isfinite(m["loss"])
    assert "reference_logps" in dm.arrays
    assert "kto_kl" in m


def test_kto_under_pp(tmp_path, devices8):
    """KTO under pipeline parallelism: single-sequence batches through the
    LM pipeline with the KTO loss hook (no chosen/rejected concat)."""
    from neuronx_distributed_training_tpu.data.modules import KTODataModule

    class CharTok:
        eos_token_id = 1
        def encode(self, s):
            return [3 + (ord(c) % 60) for c in s]

    cfg = tiny_cfg(tmp_path, max_steps=1)
    cfg["model_alignment_strategy"] = {"kto": {"kl_beta": 0.2}}
    cfg["distributed_strategy"] = {"pipeline_model_parallel_size": 2}
    cfg["model"]["num_layers"] = 4
    records = [{"prompt": f"q{i}", "completion": "yes good" if i % 2 else "no",
                "label": bool(i % 2)} for i in range(16)]
    dm = KTODataModule(records, CharTok(), seq_length=32, global_batch_size=8)
    t = Trainer.from_config(cfg, data_module=dm, enable_checkpointing=False)
    m = t.fit()
    assert np.isfinite(m["loss"])
    assert "reference_logps" in dm.arrays


class TestNormLogging:
    def test_param_and_gradient_norm_flags(self, tmp_path, devices8):
        """exp_manager.log_parameter_norm / log_gradient_norm produce per-step
        param_norm / gradient_norm in the logged metrics (reference
        base.py:397-452) — VERDICT r2 item 4."""
        cfg = tiny_cfg(tmp_path, max_steps=2)
        cfg["exp_manager"]["log_parameter_norm"] = True
        cfg["exp_manager"]["log_gradient_norm"] = True
        metrics = train(cfg)
        assert metrics["param_norm"] > 0
        assert metrics["gradient_norm"] == metrics["grad_norm"]
        exp_dir = tmp_path / "exp" / "tiny" / "version_0"
        rec = json.loads(
            (exp_dir / "metrics.jsonl").read_text().strip().splitlines()[-1]
        )
        assert rec["param_norm"] > 0 and "gradient_norm" in rec

    def test_norms_off_by_default(self, tmp_path, devices8):
        metrics = train(tiny_cfg(tmp_path, max_steps=1))
        assert "param_norm" not in metrics


class TestStreamedReferencePass:
    """The DPO/KTO frozen-policy pass streams per-batch with an incremental
    sidecar cursor, and attaches columns to the VAL module too (VERDICT r2
    item 10 + ADVICE r2)."""

    class CharTok:
        eos_token_id = 1
        def encode(self, s):
            return [3 + (ord(c) % 60) for c in s]

    def _records(self, n):
        return [{"prompt": f"q{i}", "chosen": "yes good", "rejected": "no"}
                for i in range(n)]

    def test_val_module_gets_reference_columns(self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.data.modules import DPODataModule

        cfg = tiny_cfg(tmp_path, max_steps=1)
        cfg["model_alignment_strategy"] = "dpo"
        dm = DPODataModule(self._records(16), self.CharTok(), seq_length=32,
                           global_batch_size=8)
        vdm = DPODataModule(self._records(8), self.CharTok(), seq_length=32,
                            global_batch_size=8)
        t = Trainer.from_config(cfg, data_module=dm, val_data_module=vdm,
                                enable_checkpointing=False)
        t.pre_fit(t)
        assert "reference_chosen_logps" in dm.arrays
        assert "reference_chosen_logps" in vdm.arrays  # ADVICE r2 fix
        # val eval runs without KeyError
        assert np.isfinite(t.validate(1))

    def test_sidecar_resumes_mid_pass(self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.data.modules import DPODataModule

        n = 24
        # full pass -> ground-truth columns + a complete sidecar
        cfg = tiny_cfg(tmp_path, max_steps=1)
        cfg["model_alignment_strategy"] = "dpo"
        dm = DPODataModule(self._records(n), self.CharTok(), seq_length=32,
                           global_batch_size=8)
        t = Trainer.from_config(cfg, data_module=dm)
        t.pre_fit(t)
        full = {k: dm.arrays[k].copy()
                for k in ("reference_chosen_logps", "reference_rejected_logps")}
        sidecar = tmp_path / "exp" / "tiny" / "version_0" / "checkpoints" / \
            "dpo_reference_logps.npz"
        assert sidecar.exists()
        saved = np.load(sidecar)
        assert int(saved["_done_upto"]) == n

        # truncate the sidecar to a mid-pass cursor (preemption at sample 8)
        np.savez(sidecar, _done_upto=8,
                 **{k: np.concatenate([full[k][:8], np.zeros(n - 8, full[k].dtype)])
                    for k in full})
        cfg2 = tiny_cfg(tmp_path, max_steps=1)
        cfg2["model_alignment_strategy"] = "dpo"
        dm2 = DPODataModule(self._records(n), self.CharTok(), seq_length=32,
                            global_batch_size=8)
        t2 = Trainer.from_config(cfg2, data_module=dm2)
        t2.pre_fit(t2)
        for k in full:
            np.testing.assert_allclose(dm2.arrays[k], full[k], rtol=1e-5,
                                       err_msg=f"{k} after mid-pass resume")

    def test_pass_logs_progress_and_eta(self, tmp_path, devices8, caplog):
        """The pass is not a silent multi-hour phase at scale: progress lines
        carry throughput + ETA (VERDICT r3 item 6)."""
        import logging

        from neuronx_distributed_training_tpu.data.modules import DPODataModule

        cfg = tiny_cfg(tmp_path, max_steps=1)
        cfg["model_alignment_strategy"] = "dpo"
        dm = DPODataModule(self._records(24), self.CharTok(), seq_length=32,
                           global_batch_size=8)
        t = Trainer.from_config(cfg, data_module=dm, enable_checkpointing=False)
        with caplog.at_level(
                logging.INFO,
                logger="neuronx_distributed_training_tpu.trainer.loop"):
            t.pre_fit(t)
        lines = [r.message for r in caplog.records
                 if "reference-logp pass" in r.message]
        assert lines, caplog.records
        assert any("ETA" in l and "samples/s" in l for l in lines), lines
        assert any("24/24" in l for l in lines), lines

    def test_kto_val_module_columns(self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.data.modules import KTODataModule

        recs = [{"prompt": f"p{i}", "completion": "ok sure", "label": i % 2 == 0}
                for i in range(16)]
        cfg = tiny_cfg(tmp_path, max_steps=1)
        cfg["model_alignment_strategy"] = {"kto": {"kl_beta": 0.2}}
        dm = KTODataModule(recs, self.CharTok(), seq_length=32,
                           global_batch_size=8)
        vdm = KTODataModule(recs[:8], self.CharTok(), seq_length=32,
                            global_batch_size=8)
        t = Trainer.from_config(cfg, data_module=dm, val_data_module=vdm,
                                enable_checkpointing=False)
        t.pre_fit(t)
        assert "reference_logps" in dm.arrays
        assert "reference_logps" in vdm.arrays

    def test_stale_sidecar_size_mismatch_recomputes(self, tmp_path, devices8):
        """A leftover sidecar from a differently-sized dataset must trigger a
        clean recompute, not a broadcast crash or stale attach."""
        from neuronx_distributed_training_tpu.data.modules import DPODataModule

        cfg = tiny_cfg(tmp_path, max_steps=1)
        cfg["model_alignment_strategy"] = "dpo"
        dm = DPODataModule(self._records(16), self.CharTok(), seq_length=32,
                           global_batch_size=8)
        t = Trainer.from_config(cfg, data_module=dm)
        t.pre_fit(t)
        sidecar = tmp_path / "exp" / "tiny" / "version_0" / "checkpoints" / \
            "dpo_reference_logps.npz"
        assert sidecar.exists()

        # dataset grows to 24 rows; old 16-row sidecar must be discarded
        cfg2 = tiny_cfg(tmp_path, max_steps=1)
        cfg2["model_alignment_strategy"] = "dpo"
        dm2 = DPODataModule(self._records(24), self.CharTok(), seq_length=32,
                            global_batch_size=8)
        t2 = Trainer.from_config(cfg2, data_module=dm2)
        t2.pre_fit(t2)
        assert len(dm2.arrays["reference_chosen_logps"]) == 24
