"""Ulysses (all-to-all CP) attention vs core attention: numerics on a CP mesh.

The reference has no Ulysses implementation (SURVEY.md §2.11) — this is a
TPU-native extension; parity gates against ``core_attention`` exactly like the
ring tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from neuronx_distributed_training_tpu.ops.attention import core_attention
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
from neuronx_distributed_training_tpu.parallel.ulysses import ulysses_attention

import pytest as _pytest_mark

pytestmark = _pytest_mark.mark.slow  # multi-minute parity tests; CI fast tier deselects


def make_qkv(key, b=2, s=64, h=4, kvh=None, d=16, dtype=jnp.float32):
    kvh = kvh or h
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), dtype)
    k = jax.random.normal(kk, (b, s, kvh, d), dtype)
    v = jax.random.normal(kv, (b, s, kvh, d), dtype)
    return q, k, v


@pytest.fixture(scope="module")
def cp_mesh():
    return build_mesh(MeshConfig(context_parallel_size=4))


@pytest.fixture(scope="module")
def cp_tp_mesh():
    return build_mesh(
        MeshConfig(context_parallel_size=2, tensor_model_parallel_size=2)
    )


class TestUlyssesNumerics:
    def test_matches_core_causal(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(0))
        ref = core_attention(q, k, v, causal=True)
        with cp_mesh, shd.use_mesh(cp_mesh):
            out = jax.jit(lambda *a: ulysses_attention(*a, causal=True))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_matches_core_non_causal(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(1))
        ref = core_attention(q, k, v, causal=False)
        with cp_mesh, shd.use_mesh(cp_mesh):
            out = jax.jit(lambda *a: ulysses_attention(*a, causal=False))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_gqa_kv_replication(self, cp_mesh):
        """kvh=2 < cp=4: KV heads replicate to divide cp, groups stay aligned."""
        q, k, v = make_qkv(jax.random.PRNGKey(2), h=8, kvh=2)
        ref = core_attention(q, k, v, causal=True)
        with cp_mesh, shd.use_mesh(cp_mesh):
            out = jax.jit(lambda *a: ulysses_attention(*a))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_grads_match_core(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(3), s=32)

        def loss_uly(q, k, v):
            return jnp.sum(jnp.square(ulysses_attention(q, k, v, causal=True)))

        def loss_core(q, k, v):
            return jnp.sum(jnp.square(core_attention(q, k, v, causal=True)))

        ref_grads = jax.grad(loss_core, argnums=(0, 1, 2))(q, k, v)
        with cp_mesh, shd.use_mesh(cp_mesh):
            grads = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
        for g, r in zip(grads, ref_grads):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=5e-4)

    def test_grads_match_core_with_kv_replication(self, cp_mesh):
        """Replicated-KV gradients sum over replicas (repeat transpose)."""
        q, k, v = make_qkv(jax.random.PRNGKey(7), s=32, h=8, kvh=2)

        def loss_uly(q, k, v):
            return jnp.sum(jnp.square(ulysses_attention(q, k, v, causal=True)))

        def loss_core(q, k, v):
            return jnp.sum(jnp.square(core_attention(q, k, v, causal=True)))

        ref_grads = jax.grad(loss_core, argnums=(0, 1, 2))(q, k, v)
        with cp_mesh, shd.use_mesh(cp_mesh):
            grads = jax.jit(jax.grad(loss_uly, argnums=(0, 1, 2)))(q, k, v)
        for g, r in zip(grads, ref_grads):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=5e-4)

    def test_with_tp_and_cp(self, cp_tp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(4), h=4, kvh=2)
        ref = core_attention(q, k, v, causal=True)
        with cp_tp_mesh, shd.use_mesh(cp_tp_mesh):
            out = jax.jit(lambda *a: ulysses_attention(*a))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_sliding_window(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(5))
        ref = core_attention(q, k, v, causal=True, sliding_window=16)
        with cp_mesh, shd.use_mesh(cp_mesh):
            out = jax.jit(
                lambda *a: ulysses_attention(*a, causal=True, sliding_window=16)
            )(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_sharded_inputs(self, cp_mesh):
        """Inputs already seq-sharded over context: no resharding surprises."""
        q, k, v = make_qkv(jax.random.PRNGKey(6))
        spec = P(None, "context", None, None)
        ns = NamedSharding(cp_mesh, spec)
        qs, ks, vs = (jax.device_put(x, ns) for x in (q, k, v))
        ref = core_attention(q, k, v, causal=True)
        with cp_mesh, shd.use_mesh(cp_mesh):
            out = jax.jit(lambda *a: ulysses_attention(*a))(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_cp1_fallback(self):
        q, k, v = make_qkv(jax.random.PRNGKey(8), s=16)
        ref = core_attention(q, k, v, causal=True)
        out = ulysses_attention(q, k, v, causal=True)  # no mesh active
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_indivisible_heads_raise(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(9), h=3, kvh=3)
        with cp_mesh, shd.use_mesh(cp_mesh):
            with pytest.raises(ValueError, match="divisible by tp\\*cp"):
                ulysses_attention(q, k, v)

    def test_dispatch_selects_ulysses(self, cp_mesh):
        """fusions.ulysses_attention -> attention_impl and ops.attention route."""
        from neuronx_distributed_training_tpu.models import llama
        from neuronx_distributed_training_tpu.ops.attention import attention

        cfg = llama.LlamaConfig.from_config(
            {"fusions": {"ulysses_attention": True}}, {}
        )
        assert cfg.attention_impl == "ulysses"
        q, k, v = make_qkv(jax.random.PRNGKey(10))
        ref = core_attention(q, k, v, causal=True)
        with cp_mesh, shd.use_mesh(cp_mesh):
            out = jax.jit(lambda *a: attention(*a, impl="ulysses"))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_masked_matches_core(devices8=None):
    """attention_mask stays on the ulysses path (all-gathered per rank)."""
    from neuronx_distributed_training_tpu.ops.attention import padding_mask_bias
    from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
    from neuronx_distributed_training_tpu.parallel import sharding as shd
    import numpy as np

    mesh = build_mesh(MeshConfig(context_parallel_size=4))
    q = jax.random.normal(jax.random.PRNGKey(60), (2, 64, 4, 16))
    k = jax.random.normal(jax.random.PRNGKey(61), (2, 64, 4, 16))
    v = jax.random.normal(jax.random.PRNGKey(62), (2, 64, 4, 16))
    from tests.conftest import ragged_right_pad_mask

    mask = ragged_right_pad_mask(2, 64, [50, 30])
    ref = core_attention(q, k, v, causal=True, bias=padding_mask_bias(mask))
    with mesh, shd.use_mesh(mesh):
        out = jax.jit(lambda *a: ulysses_attention(
            *a[:3], causal=True, attention_mask=a[3]))(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
