"""Zig-zag (balanced causal) ring attention: numerics + trainer wiring.

The reference's NKI ring kernel uses the contiguous layout and carries the
causal-ring imbalance; the zig-zag layout (rank r holds chunks r and 2cp-1-r)
equalizes per-rank causal work.  Not in the reference — a TPU-native extension.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from neuronx_distributed_training_tpu.ops.attention import core_attention
from neuronx_distributed_training_tpu.parallel import sharding as shd
from neuronx_distributed_training_tpu.parallel.mesh import MeshConfig, build_mesh
from neuronx_distributed_training_tpu.parallel.ring_attention import (
    zigzag_positions,
    zigzag_ring_attention,
    zigzag_transform_batch,
)

import pytest as _pytest_mark

pytestmark = _pytest_mark.mark.slow  # multi-minute parity tests


@pytest.fixture(scope="module")
def cp_mesh():
    return build_mesh(MeshConfig(context_parallel_size=4))


def make_qkv(key, b=2, s=64, h=4, kvh=None, d=16):
    kvh = kvh or h
    kq, kk, kv = jax.random.split(key, 3)
    return (jax.random.normal(kq, (b, s, h, d), jnp.float32),
            jax.random.normal(kk, (b, s, kvh, d), jnp.float32),
            jax.random.normal(kv, (b, s, kvh, d), jnp.float32))


class TestZigzagLayout:
    def test_positions_partition(self):
        pos = np.asarray(zigzag_positions(32, 4))
        assert sorted(pos.tolist()) == list(range(32))
        # rank 0's slots hold chunks 0 and 7
        assert pos[:4].tolist() == [0, 1, 2, 3]
        assert pos[4:8].tolist() == [28, 29, 30, 31]

    def test_cp1_identity(self):
        pos = np.asarray(zigzag_positions(16, 1))
        np.testing.assert_array_equal(pos, np.arange(16))

    def test_indivisible_raises(self):
        with pytest.raises(ValueError, match="divide"):
            zigzag_positions(30, 4)

    def test_transform_shifts_then_permutes(self):
        ids = jnp.arange(16, dtype=jnp.int32)[None, :]
        out = zigzag_transform_batch({"input_ids": ids, "labels": ids}, cp=2)
        pos = np.asarray(zigzag_positions(16, 2))
        np.testing.assert_array_equal(np.asarray(out["input_ids"][0]), pos)
        # label at slot p = original next token, -100 at the original final pos
        expect = np.where(pos + 1 < 16, pos + 1, -100)
        np.testing.assert_array_equal(np.asarray(out["labels"][0]), expect)


class TestZigzagNumerics:
    def test_matches_core(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(0))
        pos = zigzag_positions(64, 4)
        inv = jnp.argsort(pos)
        ref = core_attention(q, k, v, causal=True)
        qz, kz, vz = (jnp.take(x, pos, axis=1) for x in (q, k, v))
        with cp_mesh, shd.use_mesh(cp_mesh):
            oz = jax.jit(lambda *a: zigzag_ring_attention(*a))(qz, kz, vz)
        np.testing.assert_allclose(
            np.asarray(jnp.take(oz, inv, axis=1)), np.asarray(ref), atol=2e-5)

    def test_grads_match_core(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(1), s=32)
        pos = zigzag_positions(32, 4)

        def loss_zz(q, k, v):
            qz, kz, vz = (jnp.take(x, pos, axis=1) for x in (q, k, v))
            return jnp.sum(jnp.square(zigzag_ring_attention(qz, kz, vz)))

        def loss_core(q, k, v):
            return jnp.sum(jnp.square(core_attention(q, k, v, causal=True)))

        ref_g = jax.grad(loss_core, argnums=(0, 1, 2))(q, k, v)
        with cp_mesh, shd.use_mesh(cp_mesh):
            g = jax.jit(jax.grad(loss_zz, argnums=(0, 1, 2)))(q, k, v)
        for a, r in zip(g, ref_g):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r), atol=5e-4)

    def test_gqa(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(2), h=8, kvh=2)
        pos = zigzag_positions(64, 4)
        inv = jnp.argsort(pos)
        ref = core_attention(q, k, v, causal=True)
        qz, kz, vz = (jnp.take(x, pos, axis=1) for x in (q, k, v))
        with cp_mesh, shd.use_mesh(cp_mesh):
            oz = jax.jit(lambda *a: zigzag_ring_attention(*a))(qz, kz, vz)
        np.testing.assert_allclose(
            np.asarray(jnp.take(oz, inv, axis=1)), np.asarray(ref), atol=2e-5)

    def test_non_causal_rejected(self, cp_mesh):
        q, k, v = make_qkv(jax.random.PRNGKey(3), s=32)
        with cp_mesh, shd.use_mesh(cp_mesh):
            with pytest.raises(ValueError, match="causal-only"):
                zigzag_ring_attention(q, k, v, causal=False)


    def test_with_tp_and_cp(self):
        """zig-zag composes with TP (heads over model) + GQA replication."""
        mesh = build_mesh(MeshConfig(context_parallel_size=2,
                                     tensor_model_parallel_size=2))
        q, k, v = make_qkv(jax.random.PRNGKey(9), h=4, kvh=2)
        pos = zigzag_positions(64, 2)
        inv = jnp.argsort(pos)
        ref = core_attention(q, k, v, causal=True)
        qz, kz, vz = (jnp.take(x, pos, axis=1) for x in (q, k, v))
        with mesh, shd.use_mesh(mesh):
            oz = jax.jit(lambda *a: zigzag_ring_attention(*a))(qz, kz, vz)
        np.testing.assert_allclose(
            np.asarray(jnp.take(oz, inv, axis=1)), np.asarray(ref), atol=2e-5)


class TestZigzagTrainer:
    def test_loss_matches_contiguous_ring(self, devices8):
        """The full trainer loss hook (permute + pre-shift + positions) under
        zig-zag equals the contiguous-ring loss on the same batch."""
        from neuronx_distributed_training_tpu.config.loader import load_config
        from neuronx_distributed_training_tpu.trainer.loop import build_model
        from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy

        fp32 = DtypePolicy(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                           softmax_dtype=jnp.float32)
        base = {
            "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
            "num_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2, "max_position_embeddings": 64,
            "activations_checkpoint_granularity": None,
        }
        ds = {"context_parallel_size": 4}
        cfg_zz = load_config({
            "model": {**base, "fusions": {"zigzag_ring_attention": True}},
            "distributed_strategy": ds,
        })
        cfg_ring = load_config({
            "model": {**base, "fusions": {"ring_attention": True}},
            "distributed_strategy": ds,
        })
        mesh = build_mesh(MeshConfig(context_parallel_size=4))
        ids = jax.random.randint(jax.random.PRNGKey(5), (2, 64), 0, 128)
        batch = {"input_ids": ids, "labels": ids}

        mc_z, loss_z, init_z, _ = build_model(cfg_zz, fp32)
        mc_r, loss_r, init_r, _ = build_model(cfg_ring, fp32)
        params = init_z(jax.random.PRNGKey(0))
        with mesh, shd.use_mesh(mesh):
            lz, _ = jax.jit(loss_z)(params, batch, None)
            lr, _ = jax.jit(loss_r)(params, batch, None)
        np.testing.assert_allclose(float(lz), float(lr), rtol=1e-5)

    def test_trainer_end_to_end(self, tmp_path, devices8):
        from neuronx_distributed_training_tpu.config.loader import load_config
        from neuronx_distributed_training_tpu.trainer.loop import Trainer

        cfg = load_config({
            "name": "zz", "model_source": "hf", "seed": 3,
            "trainer": {"max_steps": 2, "log_every_n_steps": 1},
            "exp_manager": {"exp_dir": str(tmp_path / "exp")},
            "distributed_strategy": {"context_parallel_size": 4},
            "data": {"global_batch_size": 4, "micro_batch_size": 1,
                     "seq_length": 64, "synthetic": True},
            "model": {
                "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
                "num_layers": 2, "num_attention_heads": 4,
                "num_key_value_heads": 2, "max_position_embeddings": 64,
                "fusions": {"zigzag_ring_attention": True},
                "optim": {"name": "adamw_fp32OptState", "lr": 1e-3,
                          "sched": {"name": "LinearAnnealingWithWarmUp",
                                    "warmup_steps": 1, "max_steps": 2}},
            },
            "precision": {"type": "mixed_precision"},
        })
        t = Trainer.from_config(cfg, enable_checkpointing=False)
        m = t.fit()
        assert np.isfinite(m["loss"])

    def test_pp_guard(self, tmp_path, devices8):
        """zigzag + pp is rejected by the load-time catalog (round 3 moved
        the guard from Trainer.from_config to validate_config — it now dies
        before any compilation)."""
        from neuronx_distributed_training_tpu.config.loader import load_config

        with pytest.raises(ValueError, match="zigzag_ring_attention"):
            load_config({
            "name": "zzpp", "model_source": "hf", "seed": 3,
            "trainer": {"max_steps": 1},
            "exp_manager": {"exp_dir": str(tmp_path / "exp")},
            "distributed_strategy": {"context_parallel_size": 2,
                                     "pipeline_model_parallel_size": 2},
            "data": {"global_batch_size": 4, "micro_batch_size": 1,
                     "seq_length": 32, "synthetic": True},
            "model": {
                "vocab_size": 128, "hidden_size": 32, "intermediate_size": 64,
                "num_layers": 2, "num_attention_heads": 4,
                "num_key_value_heads": 2, "max_position_embeddings": 32,
                "fusions": {"zigzag_ring_attention": True},
                "optim": {"lr": 1e-3},
            },
            "precision": {"type": "mixed_precision"},
            })
