"""Shared renderer for the fleet-control trail (``run_summary.json``'s
``control`` section — trainer.control, docs/observability.md "Fleet
control").

Both ``tools/metrics_report.py`` and ``tools/fleet_monitor.py`` render the
operator-command acks and consensus decisions; one formatter keeps the two
from drifting when the trail schema grows a key.  Stdlib-only, like every
module the login-node tools load.
"""

from __future__ import annotations


def decision_action(d: dict) -> str:
    """The decision's one-word action for a terminal column."""
    if d.get("halt"):
        return "halt"
    if d.get("stop"):
        return "stop"
    oneshot = "/".join(k for k in ("checkpoint_now", "dump") if d.get(k))
    return oneshot or "note"


def control_trail_lines(ctl: dict) -> list[str]:
    """Body lines (no header) for a ``control`` trail dict: one line per
    command ack, one per decision.  Unreadable entries render instead of
    aborting the report."""
    lines: list[str] = []
    for c in ctl.get("commands") or []:
        if not isinstance(c, dict):
            lines.append(f"  (unreadable command entry: {c!r})")
            continue
        lines.append(f"  command {str(c.get('command', '?')):<15} "
                     f"id={str(c.get('id', '?')):<13} "
                     f"{str(c.get('status', '?')):<9} "
                     f"@ step {c.get('step', '?')}"
                     + (f"  ({c['note']})" if c.get("note") else ""))
    for d in ctl.get("decisions") or []:
        if not isinstance(d, dict):
            lines.append(f"  (unreadable decision entry: {d!r})")
            continue
        conds = ",".join(d.get("conditions") or []) or "?"
        where = "exit" if d.get("exit") else f"step {d.get('step', '?')}"
        lines.append(f"  decision @ {where:<9} {decision_action(d):<14} "
                     f"[{conds}] source={str(d.get('source', '?')):<8} "
                     f"{d.get('reason', '')}")
    if not lines:
        lines.append("  (enabled; no commands or decisions recorded)")
    return lines
