"""Shared machine-readable JSON emission for the tools/ CLIs.

Contract: when a tool is asked for JSON on stdout (``--json -``), the LAST
line of stdout is exactly one parseable JSON document — no logging line,
warning, or partial flush may land after it.  ``write_json`` enforces that
by flushing every logging handler and stderr BEFORE printing, and printing
the payload as a single compact line with its own flush.  File targets get
the indented form (humans read those).

Consumers: ``tools/preflight_audit.py --json`` and ``tools/plan.py --json``
(CI parses both with ``tail -1 | python -m json.tool``).
"""

from __future__ import annotations

import json
import logging
import sys
from typing import Any


def flush_streams() -> None:
    """Flush every logging handler + both std streams so buffered diagnostics
    cannot be interleaved after (or into) the JSON payload line."""
    for logger in [logging.getLogger()] + [
        logging.getLogger(name) for name in logging.root.manager.loggerDict
    ]:
        for handler in getattr(logger, "handlers", []):
            try:
                handler.flush()
            except Exception:  # noqa: BLE001 — best-effort, emission must win
                pass
    try:
        sys.stderr.flush()
    except Exception:  # noqa: BLE001
        pass
    try:
        sys.stdout.flush()
    except Exception:  # noqa: BLE001
        pass


def write_json(payload: Any, path: str) -> None:
    """Write ``payload`` to ``path`` (``-`` = stdout).

    Stdout form: ONE compact line, guaranteed last (streams flushed first).
    File form: indented + trailing newline, parseable as a whole file.
    """
    if path == "-":
        flush_streams()
        print(json.dumps(payload, sort_keys=False), flush=True)
        return
    with open(path, "w") as f:
        f.write(json.dumps(payload, indent=1) + "\n")
