#!/usr/bin/env python
"""Render a numerics-anomaly forensic bundle as a terminal table.

The sibling of ``metrics_report.py`` for the flight recorder's output: given
an ``anomaly_<step>/`` bundle (or a run dir, in which case the newest bundle
is picked), prints the trigger summary, the ring-buffered per-step health
trail, and the per-layer-group grad norms of the offending step — the
"what blew up, where, and what led up to it" view before reaching for replay.

    python tools/anomaly_report.py nxdt_experiments/run/version_0
    python tools/anomaly_report.py path/to/anomaly_00000042

Pure stdlib on purpose: it must run on a login node with nothing installed.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys


def _fmt(v) -> str:
    if not isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, float) and math.isnan(v):
        return "nan"
    if isinstance(v, float) and math.isinf(v):
        return "inf" if v > 0 else "-inf"
    a = abs(v)
    if a != 0 and (a >= 1e6 or a < 1e-3):
        return f"{v:.3e}"
    if float(v).is_integer():
        return f"{v:,.0f}"
    return f"{v:.4f}"


def _table(rows: list[tuple[str, ...]], header: tuple[str, ...]) -> str:
    widths = [max(len(str(r[i])) for r in [header, *rows])
              for i in range(len(header))]

    def fmt_row(r):
        return "  ".join(str(c).ljust(w) if i == 0 else str(c).rjust(w)
                         for i, (c, w) in enumerate(zip(r, widths)))

    sep = "  ".join("-" * w for w in widths)
    return "\n".join([fmt_row(header), sep, *(fmt_row(r) for r in rows)])


def find_bundle(path: str) -> str | None:
    """``path`` is a bundle dir, or a run dir holding ``anomaly_*``/``hang_*``
    bundles (newest picked)."""
    if os.path.exists(os.path.join(path, "anomaly.json")):
        return path
    if not os.path.isdir(path):
        return None

    def step_of(name: str) -> int:
        try:
            return int(name.rsplit("_", 1)[1])
        except (IndexError, ValueError):
            return -1

    # newest by STEP, not by name — lexicographic order would rank every
    # hang_* bundle above every anomaly_* bundle ("h" > "a")
    bundles = sorted(
        (e for e in os.listdir(path)
         if (e.startswith("anomaly_") or e.startswith("hang_"))
         and os.path.exists(os.path.join(path, e, "anomaly.json"))),
        key=lambda e: (step_of(e), e),
    )
    return os.path.join(path, bundles[-1]) if bundles else None


def summary_section(summary: dict) -> str:
    lines = [f"{summary.get('kind', 'anomaly')} bundle — step "
             f"{summary.get('anomaly_step')}"]
    for key in ("policy", "trigger_step", "hung_operation",
                "watchdog_timeout_seconds", "ring_buffer_steps"):
        if summary.get(key) is not None:
            lines.append(f"  {key:<24} {_fmt(summary[key])}")
    rng = summary.get("rng") or {}
    if rng:
        lines.append(f"  rng                      "
                     f"fold_in(PRNGKey({rng.get('seed', 0)}), "
                     f"{rng.get('fold_in')})")
    for key in ("model_family", "pipeline_schedule", "n_chips", "seq_len",
                "global_batch_size"):
        v = (summary.get("run_facts") or {}).get(key)
        if v is not None:
            lines.append(f"  {key:<24} {_fmt(v)}")
    if summary.get("compile_census"):
        lines.append(f"  compile census           {summary['compile_census']}")
    return "\n".join(lines)


def ring_section(ring: list[dict]) -> str:
    if not ring:
        return ""
    cols = ("loss", "grad_norm", "health/updates_finite",
            "health/param_norm", "health/nonfinite_count")
    rows = []
    prev_pnorm = None
    for e in ring:
        m = e.get("metrics") or {}
        pnorm = m.get("health/param_norm")
        drift = ""
        if isinstance(pnorm, (int, float)) and isinstance(prev_pnorm, (int, float)):
            drift = _fmt(pnorm - prev_pnorm)
        prev_pnorm = pnorm if isinstance(pnorm, (int, float)) else prev_pnorm
        rows.append((str(e.get("step")),
                     *(_fmt(m[c]) if c in m else "-" for c in cols),
                     drift))
    return ("\nring buffer (oldest first)\n"
            + _table(rows, ("step", "loss", "grad_norm", "finite",
                            "param_norm", "nonfinite", "pnorm_drift")))


def group_norms_section(ring: list[dict], anomaly_step: int) -> str:
    entry = next((e for e in ring if e.get("step") == anomaly_step),
                 ring[-1] if ring else None)
    if not entry:
        return ""
    prefix = "health/grad_norm/"
    groups = {k[len(prefix):]: v for k, v in (entry.get("metrics") or {}).items()
              if k.startswith(prefix)}
    if not groups:
        return ""
    rows = [(g, _fmt(v)) for g, v in sorted(groups.items())]
    return (f"\nper-group grad norms (step {entry.get('step')})\n"
            + _table(rows, ("group", "grad_norm")))


def tensorstats_section(ring: list[dict]) -> str:
    """Per-layer-group dynamic-range trail (``telemetry.tensorstats``) —
    the "which group's gradients underflowed / blew up on the way in"
    companion to the param-norm drift column above."""
    prefix = "tensorstats/pre/"
    groups = sorted({k[len(prefix):].rsplit("/", 1)[0]
                     for e in ring for k in (e.get("metrics") or {})
                     if k.startswith(prefix)})
    if not groups:
        return ""
    shown = groups[:6]  # keep the table terminal-width sane
    rows = []
    for e in ring:
        m = e.get("metrics") or {}
        rows.append((str(e.get("step")),
                     *(_fmt(m[f"{prefix}{g}/absmax"])
                       if f"{prefix}{g}/absmax" in m else "-"
                       for g in shown)))
    out = ("\ntensorstats absmax trail (pre-clip grads, oldest first)\n"
           + _table(rows, ("step", *shown)))
    if len(groups) > len(shown):
        out += f"\n  (+{len(groups) - len(shown)} more groups not shown)"
    last = ring[-1].get("metrics") or {}
    urows = [(g, _fmt(last.get(f"{prefix}{g}/rms", "-")),
              _fmt(last.get(f"{prefix}{g}/zero_frac", "-")),
              _fmt(last.get(f"{prefix}{g}/subnormal_frac", "-")))
             for g in groups
             if any(f"{prefix}{g}/{s}" in last
                    for s in ("rms", "zero_frac", "subnormal_frac"))]
    if urows:
        out += (f"\n\ntensorstats dynamic range (step "
                f"{ring[-1].get('step')})\n"
                + _table(urows, ("group", "rms", "zero_frac",
                                 "subnormal_frac")))
    return out


def fingerprint_section(ring: list[dict], anomaly_step: int) -> str:
    entry = next((e for e in ring if e.get("step") == anomaly_step), None)
    fp = (entry or {}).get("fingerprint")
    if not fp:
        return ""
    rows = [(k, v) for k, v in sorted(fp.items())]
    return (f"\nbatch fingerprint (step {anomaly_step})\n"
            + _table(rows, ("leaf", "dtype[shape]")))


def render(bundle_dir: str) -> str:
    with open(os.path.join(bundle_dir, "anomaly.json")) as f:
        summary = json.load(f)
    ring: list[dict] = []
    ring_path = os.path.join(bundle_dir, "ring.json")
    if os.path.exists(ring_path):
        with open(ring_path) as f:
            ring = json.load(f)
    step = int(summary.get("anomaly_step", -1))
    parts = [summary_section(summary), ring_section(ring),
             group_norms_section(ring, step), tensorstats_section(ring),
             fingerprint_section(ring, step)]
    stacks = os.path.join(bundle_dir, "stacks.txt")
    if os.path.exists(stacks):
        parts.append(f"\npython stacks: {stacks}")
    return "\n".join(p for p in parts if p)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="anomaly_<step>/ bundle dir, or a run dir "
                                 "(newest bundle picked)")
    args = ap.parse_args(argv)
    bundle = find_bundle(args.path)
    if bundle is None:
        print(f"anomaly_report: no forensic bundle at {args.path}",
              file=sys.stderr)
        return 2
    print(render(bundle))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
