#!/usr/bin/env python
"""Offline checkpoint-integrity verification of a run dir.

Walks every retained step of a checkpoint directory (or one ``--step``),
re-reads each digested item template-free, re-hashes, and compares against
the ``integrity`` sidecar saved with the step (docs/elasticity.md
"Integrity & walk-back").  Exit status: 0 when every step verifies (``ok``
or pre-integrity ``legacy``), 1 when any step is corrupt or nothing was
found to verify.

    python tools/ckpt_verify.py <run_dir|checkpoint_dir>
    python tools/ckpt_verify.py <dir> --step 40
    python tools/ckpt_verify.py <dir> --json -          # _jsonout contract
    python tools/ckpt_verify.py <dir> --quarantine      # apply the ledger

``--quarantine`` applies the same quarantine auto-resume would: corrupt
step dirs are renamed out of the discovery namespace and recorded in
``quarantine_ledger.json`` — the next resume walks straight to the newest
good step without re-verifying the corpse.  Without the flag the tool only
REPORTS (safe on a live run's directory).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
from pathlib import Path
from typing import Any, Optional

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # tools/_jsonout

logger = logging.getLogger("nxdt.ckpt_verify")


def resolve_checkpoint_dir(path: str | Path) -> Optional[Path]:
    """Accept a run dir (``<...>/version_N``), an experiment base dir, or a
    checkpoint dir directly — the same layout ``ExpManager`` writes."""
    p = Path(path)
    if not p.is_dir():  # missing, or an operator slip like .../metrics.jsonl
        return None
    if (p / "checkpoints").is_dir():
        return p / "checkpoints"
    if p.name == "checkpoints" or any(c.name.isdigit() for c in p.iterdir()
                                      if c.is_dir()):
        return p
    # experiment base dir: newest version_N (same parse as ExpManager)
    from neuronx_distributed_training_tpu.trainer.exp_manager import (
        latest_version,
    )

    v = latest_version(p)
    if v is not None and (p / f"version_{v}" / "checkpoints").is_dir():
        return p / f"version_{v}" / "checkpoints"
    return None


def verify_dir(ck_dir: Path, *, step: Optional[int] = None,
               quarantine: bool = False) -> dict[str, Any]:
    """Verify all retained steps (or one) under ``ck_dir``; returns the
    report payload (the CLI's JSON)."""
    from neuronx_distributed_training_tpu.checkpoint import integrity as I

    mgr = I.open_readonly_manager(ck_dir)
    quarantined: list[int] = []
    verdicts = []
    try:
        steps = sorted(mgr.all_steps() or [])
        if step is not None:
            if int(step) not in steps:
                return {"ok": False, "checkpoint_dir": str(ck_dir),
                        "error": f"step {step} not found (retained: {steps})"}
            steps = [int(step)]
        for s in steps:
            v = I.verify_step(ck_dir, s, mgr=mgr)
            verdicts.append(v)
            tag = {"ok": "OK", "legacy": "LEGACY (no sidecar — unverified)",
                   "corrupt": "CORRUPT", "gone": "GONE"}[v.status]
            print(f"step {s:>8}: {tag}  "
                  f"({v.groups_checked} group(s), {v.seconds:.2f}s)")
            for f in v.failures:
                print(f"             - {f}")
            if v.status == "corrupt" and quarantine:
                I.apply_quarantine(ck_dir, s, reason=v.failures[0]
                                   if v.failures else "digest-mismatch",
                                   failures=v.failures)
                quarantined.append(s)
        if quarantined:
            mgr.reload()
    finally:
        try:
            mgr.close()
        except Exception:  # noqa: BLE001 — read-only teardown
            pass
    ledger = I.read_ledger(ck_dir)
    corrupt = [v for v in verdicts if v.status == "corrupt"]
    return {
        "ok": bool(verdicts) and not corrupt,
        "checkpoint_dir": str(ck_dir),
        "steps": [v.to_dict() for v in verdicts],
        "corrupt_steps": [v.step for v in corrupt],
        "legacy_steps": [v.step for v in verdicts if v.status == "legacy"],
        "quarantined": quarantined,
        "ledger_entries": len(ledger),
    }


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="run dir, experiment base dir, or "
                                 "checkpoint dir")
    ap.add_argument("--step", type=int, default=None,
                    help="verify only this retained step")
    ap.add_argument("--quarantine", action="store_true",
                    help="rename corrupt steps out of discovery + write the "
                         "quarantine ledger (default: report only)")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the report as JSON ('-' = stdout, last "
                         "line, tools/_jsonout contract)")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    # verification is a host-side read: stay off any TPU the box may have
    # (same dance as tools/elastic_drill.py — sitecustomize imported jax)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=1").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    ck_dir = resolve_checkpoint_dir(args.path)
    if ck_dir is None:
        logger.error("no checkpoint directory under %s", args.path)
        report: dict[str, Any] = {
            "ok": False, "error": f"no checkpoint directory under {args.path}"}
    else:
        report = verify_dir(ck_dir, step=args.step,
                            quarantine=args.quarantine)
    if args.json:
        from _jsonout import write_json

        write_json(report, args.json)
    return 0 if report.get("ok") else 1


if __name__ == "__main__":
    raise SystemExit(main())
