#!/usr/bin/env python
"""Interconnect microbenchmark — measured collective bandwidth per mesh axis.

Sweeps {all-reduce, all-gather, reduce-scatter, collective-permute,
all-to-all} x mesh axis x message size through the repo's REAL mesh
machinery (``parallel.sharding.shard_map`` over a ``parallel.mesh`` mesh),
fits per-axis bandwidth + latency from the timed points (the same
bus-bandwidth conventions ``autotune.cost_model._ring_seconds`` prices
with), probes per-device timing skew, and writes a byte-stable
``comms_summary.json`` — the measured interconnect the planner can
calibrate against (``tools/plan.py --calibrate-from``) and the perf
contract gates (PC204, committed ``cpu_comms`` baseline).

    python tools/comms_bench.py --smoke --json -
    python tools/comms_bench.py --devices 8 --tp 2 --pp 2 --out run_dir
    python tools/comms_bench.py --sizes 1048576,4194304 --reps 5
    python tools/plan.py --config cfg.yaml --calibrate-from comms_summary.json

A device whose timing sits beyond ``--skew-threshold`` x the median lands
in the summary's ``findings`` as a ``degraded_link`` — and
``telemetry.comms.degraded_link_alert_rule()`` is the worked in-loop alert
rule for the same signal (docs/observability.md 'Interconnect
observatory').  ``--json`` writes through the shared ``tools/_jsonout.py``
writer: with ``--json -`` the LAST stdout line is guaranteed parseable
JSON (a bench-style line: ``metric=comms_bench_sweep`` + the
``perf_contract`` verdict).
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # tools/_jsonout


def _fmt(v, nd=3) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        a = abs(v)
        if a != 0 and (a >= 1e6 or a < 1e-3):
            return f"{v:.3e}"
        return f"{v:.{nd}f}"
    return str(v)


def render(summary: dict) -> str:
    """Human rendering of a comms summary (the full table lives in
    tools/comms_report.py — this is the bench-side echo)."""
    prior = dict(summary.get("prior") or {})
    lines = [f"interconnect sweep — topology={summary.get('topology')} "
             f"prior={float(prior.get('ici_bandwidth_bytes') or 0) / 1e9:g}"
             f" GB/s"]
    for axis, entry in sorted((summary.get("axes") or {}).items()):
        fit = entry.get("fit") or {}
        head = (f"  {axis} (mesh axis {entry.get('mesh_axis')}, "
                f"size {entry.get('size')}):")
        if fit.get("bandwidth_bytes_per_s"):
            bw = float(fit["bandwidth_bytes_per_s"]) / 1e9
            lat = float(fit.get("latency_seconds") or 0) * 1e6
            head += f"  bw={bw:.3f} GB/s  lat={lat:.1f}us"
            if entry.get("bandwidth_ratio") is not None:
                head += f"  measured/prior={entry['bandwidth_ratio']:.2f}"
        lines.append(head)
        for row in entry.get("sweep") or ():
            lines.append(
                f"    {row['collective']:<18s} payload="
                f"{int(row['payload_bytes']):>9d}B  bus="
                f"{_fmt(row.get('bus_gbps'))} GB/s  t="
                f"{_fmt(row.get('seconds_median'), 6)}s")
    for f in summary.get("findings") or ():
        lines.append(f"  FINDING [{f.get('kind')}]: {f.get('message')}")
    if not summary.get("findings"):
        lines.append("  no degraded-link findings")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--platform", default="cpu", choices=["cpu", "tpu"],
                    help="jax platform (default cpu: the sweep is testable "
                         "on virtual host devices)")
    ap.add_argument("--devices", type=int, default=8,
                    help="virtual CPU device count (cpu platform only; "
                         "default 8 — tp=2 x pp=2 x dp=2)")
    ap.add_argument("--tp", type=int, default=2,
                    help="tensor-parallel degree of the sweep mesh")
    ap.add_argument("--pp", type=int, default=2,
                    help="pipeline-parallel degree of the sweep mesh")
    ap.add_argument("--cp", type=int, default=1,
                    help="context-parallel degree of the sweep mesh")
    ap.add_argument("--ep", type=int, default=1,
                    help="expert-parallel degree of the sweep mesh")
    ap.add_argument("--sizes", default="1048576,4194304",
                    help="comma-separated payload sizes in bytes "
                         "(default 1MiB,4MiB)")
    ap.add_argument("--kinds", default=None,
                    help="comma-separated collective kinds to sweep "
                         "(default: every kind the axis carries, per "
                         "utils.debug.AXIS_COLLECTIVE_KINDS)")
    ap.add_argument("--reps", type=int, default=3,
                    help="timed repetitions per point (median wins)")
    ap.add_argument("--warmup", type=int, default=1,
                    help="untimed warmup calls per point (compile)")
    ap.add_argument("--smoke", action="store_true",
                    help="fast CI shape: 64K/256K payloads, 2 reps — the "
                         "verify-gate invocation")
    ap.add_argument("--no-skew", dest="skew", action="store_false",
                    help="skip the per-device timing-skew probe")
    ap.add_argument("--skew-threshold", type=float, default=None,
                    help="flag a device beyond this multiple of the median "
                         "probe time as a degraded link (default "
                         "telemetry.comms.SKEW_REL_THRESHOLD)")
    ap.add_argument("--out", default="comms_summary.json", metavar="PATH",
                    help="where to write comms_summary.json (a directory "
                         "gets the canonical file name; default ./"
                         "comms_summary.json)")
    ap.add_argument("--contract-key", default=None, metavar="NAME",
                    help="perf-contract baseline key override (default: "
                         "derived from the device identity, e.g. "
                         "cpu_comms)")
    ap.add_argument("--json", metavar="PATH",
                    help="bench-style JSON line ('-' = stdout last line, "
                         "the shared tools/_jsonout contract)")
    args = ap.parse_args(argv)

    # size the virtual CPU world BEFORE jax initializes
    if args.platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.devices}").strip()

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from neuronx_distributed_training_tpu.autotune.topology import (
        resolve_topology,
    )
    from neuronx_distributed_training_tpu.parallel.mesh import (
        MeshConfig,
        build_mesh,
    )
    from neuronx_distributed_training_tpu.telemetry import comms

    devices = jax.devices()
    try:
        mesh = build_mesh(MeshConfig(
            tensor_model_parallel_size=args.tp,
            pipeline_model_parallel_size=args.pp,
            context_parallel_size=args.cp,
            expert_model_parallel_size=args.ep,
        ), devices)
    except (ValueError, AssertionError) as e:
        print(f"comms_bench: mesh build failed for {len(devices)} devices: "
              f"{e}", file=sys.stderr)
        if args.json:
            from _jsonout import write_json

            write_json({"ok": False, "metric": "comms_bench_sweep",
                        "error": str(e),
                        "perf_contract": {"verdict": "no_measurement"}},
                       args.json)
        return 2

    if args.smoke:
        sizes = (1 << 16, 1 << 18)
        reps, warmup = 2, 1
    else:
        sizes = tuple(int(s) for s in args.sizes.split(",") if s)
        reps, warmup = args.reps, args.warmup
    kinds = tuple(k.strip() for k in args.kinds.split(",") if k.strip()) \
        if args.kinds else None

    axis_results = comms.run_comms_sweep(
        mesh, sizes_bytes=sizes, kinds=kinds, warmup=warmup, reps=reps)
    topo = resolve_topology(device=devices[0])
    skew = comms.measure_device_skew(devices) if args.skew else None
    summary = comms.build_comms_summary(
        axis_results, topology_name=topo.name,
        prior_bandwidth_bytes=topo.ici_bandwidth_bytes,
        prior_latency_seconds=topo.ici_latency_seconds,
        device_skew=skew,
        skew_rel_threshold=(args.skew_threshold
                            if args.skew_threshold is not None
                            else comms.SKEW_REL_THRESHOLD))

    out = Path(args.out)
    if out.is_dir() or args.out.endswith(os.sep):
        out = out / comms.COMMS_SUMMARY_NAME
    comms.write_comms_summary(summary, out)

    print(render(summary))
    print(f"wrote {out}")

    facts_block = comms.bench_comms_facts(summary)
    ratios = [a.get("bandwidth_ratio")
              for a in (facts_block.get("axes") or {}).values()
              if a.get("bandwidth_ratio") is not None]
    payload = {
        "metric": "comms_bench_sweep",
        "value": round(min(ratios), 6) if ratios else 0.0,
        "unit": "min_axis_bandwidth_measured_over_prior",
        "device": getattr(devices[0], "device_kind", devices[0].platform),
        "mesh": {k: int(v) for k, v in dict(mesh.shape).items() if v > 1},
        "sizes_bytes": list(sizes),
        "comms": facts_block,
        "findings": summary.get("findings") or [],
        "comms_summary_path": str(out),
        "note": ("bus-bandwidth conventions (all-reduce 2B(n-1)/n, "
                 "AG/RS/A2A B(n-1)/n, permute B) — the same factors the "
                 "cost model's _ring_seconds prices with"),
    }
    # the perf-contract verdict: PC204 gates the measured bandwidth against
    # the committed per-topology baseline (cpu_comms on the CPU smoke)
    try:
        from neuronx_distributed_training_tpu.analysis import (
            perf_contract as _pc,
        )

        facts = _pc.perf_facts_from_bench(payload)
        key = args.contract_key or _pc.default_key(facts)
        payload["perf_contract"] = _pc.bench_verdict(key, facts)
        print(f"perf contract [{key}]: "
              f"{payload['perf_contract']['verdict']}")
    except Exception as e:  # noqa: BLE001 — the line must survive, but the
        # verdict's absence must be explained
        payload["perf_contract"] = {
            "verdict": "unavailable",
            "error": f"{type(e).__name__}: {e}"[:300],
        }

    if args.json:
        from _jsonout import write_json

        write_json(payload, args.json)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
