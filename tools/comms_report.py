#!/usr/bin/env python
"""Interconnect observatory report — render measured collective bandwidth.

Reads either artifact the observatory produces and renders it for a
terminal (stdlib-only — runs on a login node with nothing installed):

- a ``comms_summary.json`` (``tools/comms_bench.py``): per-axis
  bandwidth/latency fits with the raw sweep curve behind each fit,
  measured/prior ratios, and per-device skew findings naming a degraded
  link;
- a run dir (or ``run_summary.json`` / ``trace_summary.json``): the
  trainer's in-loop join — per-collective-class achieved_gbps and
  efficiency vs the topology peak (``telemetry.comms.comms_section``).

    python tools/comms_report.py comms_summary.json
    python tools/comms_report.py nxdt_experiments/run/version_0
    python tools/comms_report.py run_dir --json -    # last line = JSON

``--json`` writes through the shared ``tools/_jsonout.py`` writer: with
``--json -`` the LAST stdout line is guaranteed parseable JSON.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))  # tools/_jsonout

from _jsonout import write_json  # noqa: E402


def _fmt(v, nd=3) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        a = abs(v)
        if a != 0 and (a >= 1e6 or a < 1e-3):
            return f"{v:.3e}"
        return f"{v:.{nd}f}"
    return str(v)


def _table(rows, headers) -> str:
    cols = [[str(h)] + [str(r[i]) for r in rows]
            for i, h in enumerate(headers)]
    widths = [max(len(c) for c in col) for col in cols]
    out = []
    for j in range(len(rows) + 1):
        out.append("  " + "  ".join(
            cols[i][j].ljust(widths[i]) for i in range(len(headers))))
        if j == 0:
            out.append("  " + "  ".join("-" * w for w in widths))
    return "\n".join(out)


def render_summary(summary: dict) -> str:
    """A comms_summary.json (the standalone sweep's artifact)."""
    prior = dict(summary.get("prior") or {})
    prior_bw = float(prior.get("ici_bandwidth_bytes") or 0.0)
    parts = [f"interconnect observatory — topology="
             f"{summary.get('topology')} prior={prior_bw / 1e9:g} GB/s "
             f"bus bandwidth, {float(prior.get('ici_latency_seconds') or 0) * 1e6:g}us latency"]
    axes = summary.get("axes") or {}
    fit_rows = []
    for axis, entry in sorted(axes.items()):
        fit = entry.get("fit") or {}
        bw = fit.get("bandwidth_bytes_per_s")
        fit_rows.append((
            axis, entry.get("mesh_axis") or "-", entry.get("size") or "-",
            _fmt(float(bw) / 1e9) if bw else "-",
            _fmt(float(fit.get("latency_seconds") or 0.0) * 1e6, 1)
            if bw else "-",
            _fmt(entry.get("bandwidth_ratio"), 2),
            fit.get("n_points") or 0))
    if fit_rows:
        parts.append("per-axis fit (t = bytes/bw + hops x latency over the "
                     "sweep points; ratio = measured/prior):")
        parts.append(_table(fit_rows, ("axis", "mesh", "n", "bw_gbps",
                                       "lat_us", "ratio", "points")))
    for axis, entry in sorted(axes.items()):
        sweep = entry.get("sweep") or []
        if not sweep:
            continue
        rows = [(r.get("collective"), r.get("payload_bytes"),
                 _fmt(r.get("bus_gbps")), _fmt(r.get("seconds_median"), 6),
                 _fmt(r.get("seconds_min"), 6), r.get("reps"))
                for r in sweep]
        parts.append(f"{axis}-axis sweep:")
        parts.append(_table(rows, ("collective", "payload_B", "bus_gbps",
                                   "t_med_s", "t_min_s", "reps")))
    skew = summary.get("device_skew") or {}
    per_dev = skew.get("per_device") or {}
    if per_dev:
        med = skew.get("median_seconds")
        rows = [(d, _fmt(t, 6),
                 _fmt(t / med, 2) if med else "-")
                for d, t in sorted(per_dev.items(),
                                   key=lambda kv: -float(kv[1]))]
        parts.append(f"per-device timing probe (median={_fmt(med, 6)}s, "
                     f"degraded beyond {_fmt(skew.get('rel_threshold'), 2)}x"
                     f" median):")
        parts.append(_table(rows, ("device", "seconds", "x_median")))
    findings = summary.get("findings") or []
    for f in findings:
        parts.append(f"FINDING [{f.get('kind')}] {f.get('message')}")
    if not findings:
        parts.append("no degraded-link findings")
    return "\n".join(parts)


def render_section(section: dict, origin: str) -> str:
    """The trainer's in-loop ``comms`` section (run/trace summary)."""
    parts = [f"in-loop achieved bandwidth ({origin}) — topology="
             f"{section.get('topology')} peak="
             f"{_fmt(section.get('peak_bandwidth_gbps'))} GB/s over "
             f"{section.get('window_steps')} traced steps"]
    rows = []
    for kind, e in sorted((section.get("classes") or {}).items()):
        rows.append((kind, _fmt(e.get("bus_bytes_per_step"), 0),
                     _fmt(e.get("wire_seconds_per_step"), 6),
                     _fmt(e.get("achieved_gbps")),
                     f"{100 * e['efficiency']:.1f}%"
                     if e.get("efficiency") is not None else "-",
                     e.get("count") or 0))
    if rows:
        parts.append("per-collective-class (bus bytes from the cost model's "
                     "byte volumes, wire seconds from the device trace):")
        parts.append(_table(rows, ("class", "bus_B_per_step", "wire_s",
                                   "achieved_gbps", "efficiency", "ops")))
    else:
        parts.append("comms section carries no joined classes")
    return "\n".join(parts)


def load_source(path: str) -> tuple[dict, str, str]:
    """(payload, kind, origin) — kind is 'summary' (standalone sweep) or
    'section' (in-loop join).  Raises ValueError on anything unusable."""
    p = Path(path)
    if p.is_dir():
        for name in ("comms_summary.json", "run_summary.json",
                     "trace_summary.json"):
            f = p / name
            if f.exists():
                return load_source(str(f))
        raise ValueError(
            f"{p}: no comms_summary.json, run_summary.json, or "
            f"trace_summary.json — nothing to render")
    try:
        doc = json.loads(p.read_text())
    except (OSError, ValueError) as e:
        raise ValueError(f"unreadable JSON at {p}: {e}") from e
    if not isinstance(doc, dict):
        raise ValueError(f"{p}: expected a JSON object")
    if doc.get("kind") == "comms_summary" or (
            isinstance(doc.get("axes"), dict)
            and isinstance(doc.get("prior"), dict)):
        return doc, "summary", p.name
    section = doc.get("comms")
    if isinstance(section, dict) and section.get("classes"):
        return section, "section", p.name
    raise ValueError(
        f"{p}: neither a comms summary nor a run/trace summary with a "
        f"'comms' section (run tools/comms_bench.py, or a traced run with "
        f"telemetry.trace enabled)")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("source",
                    help="comms_summary.json, a run dir, or a run/trace "
                         "summary carrying a 'comms' section")
    ap.add_argument("--json", metavar="PATH",
                    help="machine-readable payload ('-' = stdout last "
                         "line, the shared tools/_jsonout contract)")
    args = ap.parse_args(argv)

    try:
        payload, kind, origin = load_source(args.source)
    except ValueError as e:
        print(f"comms_report: {e}", file=sys.stderr)
        if args.json:
            write_json({"ok": False, "error": str(e)}, args.json)
        return 2
    if kind == "summary":
        print(render_summary(payload))
    else:
        print(render_section(payload, origin))
    if args.json:
        write_json({"ok": True, "kind": kind, "origin": origin,
                    "payload": payload}, args.json)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
