#!/usr/bin/env python
"""Preemption drill harness: kill (or gracefully preempt) a tiny-llama run at
a configurable point, resume it — optionally on a DIFFERENT device count, so
the restart-time autotune replanner has to re-mesh — and prove the resumed
loss trajectory matches an uninterrupted control run at pinned tolerance.

This is the fleet-survivability acceptance gate for the elastic resume path
(docs/elasticity.md): a health-halt or SIGTERM must leave the run one
auto-resume away from continuing, whatever the post-shrink fleet looks like.

    python tools/elastic_drill.py --smoke             # CI gate: dp 4 -> 2 kill drill
    python tools/elastic_drill.py --at-step 3 --phase save --mode sigterm \
        --world 4 --resume-world 8 --json -

The drill runs single-process on the virtual CPU mesh (the same 8-device
harness the test suite uses): "world size" is a device-subset choice, the
kill is :class:`~neuronx_distributed_training_tpu.trainer.elastic.
SimulatedPreemption` raised at the injection point — everything downstream of
the signal (drain, manifest, replan, resharded restore, goodput accounting)
is the REAL production path.  ``tests/test_elastic.py`` drives the same
:func:`run_drill` entry, so the CLI and the regression suite cannot drift.

A completed drill records ``restart_cost_seconds`` / ``goodput_fraction`` in
``bench_results/last_drill.json``; ``bench.py`` picks the file up and carries
both in its JSON line, so restart cost is visible in the bench trajectory.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from pathlib import Path
from typing import Any, Optional

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # tools/_jsonout

logger = logging.getLogger("nxdt.elastic_drill")

#: where the last completed drill's headline numbers land (bench.py reads it)
LAST_DRILL_PATH = "bench_results/last_drill.json"

#: loss-trajectory pin for cross-dp resumes: the resumed run re-reduces the
#: same global batches over a different dp grouping, so per-step losses agree
#: to reduction-order noise, not bitwise (same-dp resumes ARE bitwise and the
#: harness asserts exact equality there)
DEFAULT_LOSS_TOL = 2e-3


def tiny_llama_config(workdir: str | Path, *, name: str = "drill",
                      max_steps: int = 6, save_every: int = 2,
                      seed: int = 7) -> dict[str, Any]:
    """The drill's tiny-llama raw config mapping: synthetic deterministic
    data (content is a pure function of row index — identical batches at any
    dp), per-step logging, goodput telemetry on, elastic resume on."""
    return {
        "name": name,
        "model_source": "hf",
        "seed": seed,
        "trainer": {"max_steps": max_steps, "log_every_n_steps": 1},
        "exp_manager": {
            "exp_dir": str(workdir),
            "resume_if_exists": True,
            "checkpoint_callback_params": {
                "save_top_k": 2, "every_n_train_steps": save_every,
                "async_checkpointing": True,
            },
            "elastic": {"enabled": True, "grace_period_seconds": 10.0},
            "telemetry": {"spans": True, "goodput": True,
                          "compile_census": False, "mfu": False},
        },
        "distributed_strategy": {"tensor_model_parallel_size": 1,
                                 "zero1": True},
        "data": {"global_batch_size": 8, "micro_batch_size": 1,
                 "seq_length": 32, "synthetic": True},
        "model": {
            "vocab_size": 128, "hidden_size": 64, "intermediate_size": 128,
            "num_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2, "max_position_embeddings": 32,
            "optim": {"name": "adamw_fp32OptState", "lr": 1e-3,
                      "sched": {"name": "LinearAnnealingWithWarmUp",
                                "warmup_steps": 2, "max_steps": max_steps}},
        },
        "precision": {"type": "mixed_precision"},
    }


def read_losses(run_dir: str | Path) -> dict[int, float]:
    """``{step: loss}`` from a run dir's ``metrics.jsonl`` — last record per
    step wins (a resumed run re-logs the steps it re-trains)."""
    out: dict[int, float] = {}
    path = Path(run_dir) / "metrics.jsonl"
    if not path.exists():
        return out
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn tail line from a killed run
        if isinstance(rec.get("step"), int) and "loss" in rec:
            out[rec["step"]] = float(rec["loss"])
    return out


def _run_dir(cfg: Any) -> Path:
    from neuronx_distributed_training_tpu.trainer.exp_manager import (
        experiment_base_dir,
        latest_version,
    )

    base = experiment_base_dir(dict(cfg))
    v = latest_version(base)
    return base / f"version_{v if v is not None else 0}"


def run_segment(raw_cfg: dict, devices: list, *,
                fault: Optional[Any] = None,
                replan_world: Optional[int] = None,
                peer_words: Optional[Any] = None) -> dict[str, Any]:
    """One trainer incarnation of the drill: build (optionally after a
    restart-time replan for ``replan_world`` chips), attach the fault
    injector, run ``fit()``, and report what happened.

    Returns ``{"killed": bool, "metrics": dict|None, "trainer": Trainer,
    "run_dir": Path, "replanned": bool, "record": dict|None}`` — ``killed``
    is True when the injected :class:`SimulatedPreemption` fired (the
    simulated SIGKILL: fit() died, teardown still drained the async save)."""
    from neuronx_distributed_training_tpu.config.loader import load_config
    from neuronx_distributed_training_tpu.trainer.elastic import (
        SimulatedPreemption,
        maybe_replan,
    )
    from neuronx_distributed_training_tpu.trainer.loop import Trainer

    cfg = load_config(raw_cfg)
    record, itrail = None, None
    if replan_world is not None:
        result = maybe_replan(cfg, int(replan_world))
        cfg, record, itrail = result.cfg, result.record, result.integrity_trail
    trainer = Trainer.from_config(cfg, devices=list(devices))
    if record is not None:
        trainer.replan_record = record
    if itrail is not None:
        trainer.discovery_integrity_trail = itrail
    if fault is not None:
        trainer.fault_injector = fault
    if peer_words is not None:
        # the control plane's simulated-peer seam: extra control-word bits
        # standing in for other hosts' contributions on this single-process
        # mesh (trainer.control, docs/observability.md "Fleet control")
        trainer.control_peer_words = peer_words
    killed, metrics = False, None
    try:
        metrics = trainer.fit()
    except SimulatedPreemption as e:
        killed = True
        logger.info("drill: %s", e)
    return {"killed": killed, "metrics": metrics, "trainer": trainer,
            "run_dir": _run_dir(cfg), "replanned": record is not None,
            "record": record}


def _tree_max_diff(a: Any, b: Any) -> float:
    import jax
    import numpy as np

    diffs = jax.tree_util.tree_map(
        lambda x, y: float(np.max(np.abs(
            np.asarray(x, dtype=np.float64) - np.asarray(y, np.float64))))
        if np.asarray(x).size else 0.0,
        a, b,
    )
    return max(jax.tree_util.tree_leaves(diffs), default=0.0)


def run_drill(workdir: str | Path, *, at_step: int = 3, phase: str = "step",
              mode: str = "kill", world: int = 4,
              resume_world: Optional[int] = 2, total_steps: int = 6,
              save_every: int = 2, loss_tol: float = DEFAULT_LOSS_TOL,
              record_path: Optional[str] = None) -> dict[str, Any]:
    """The full drill: control run, injected fault, resume (replanned when
    the world changed), trajectory + state comparison.  Raises
    ``AssertionError`` with a diagnostic on any continuity violation.

    Returns the drill report (the CLI's JSON payload)."""
    import jax

    from neuronx_distributed_training_tpu.trainer.elastic import FaultInjector

    devices = jax.devices()
    resume_world = int(resume_world if resume_world is not None else world)
    if max(world, resume_world) > len(devices):
        raise ValueError(
            f"drill wants {max(world, resume_world)} devices, "
            f"have {len(devices)}")
    workdir = Path(workdir)

    # 1. control: uninterrupted run at the original world size
    control = run_segment(
        tiny_llama_config(workdir / "control", max_steps=total_steps,
                          save_every=save_every),
        devices[:world])
    assert control.get("metrics"), "control run produced no metrics"

    # 2. the doomed run: same config, fault injected.  A restore-phase fault
    # belongs to the RESUME incarnation (a fresh start never restores), so
    # for phase="restore" the doomed run is interrupted by a plain step kill
    # — its job is only to leave an interrupted run + checkpoint behind.
    drill_cfg = tiny_llama_config(workdir / "drill", max_steps=total_steps,
                                  save_every=save_every)
    doomed_fault = (FaultInjector(at_step=at_step, mode="kill", phase="step")
                    if phase == "restore"
                    else FaultInjector(at_step=at_step, mode=mode, phase=phase))
    doomed = run_segment(drill_cfg, devices[:world], fault=doomed_fault)
    if mode == "kill" or phase == "restore":
        assert doomed["killed"], (
            f"FaultInjector({doomed_fault.mode}, {doomed_fault.phase}, "
            f"step {at_step}) never fired — the drill tested nothing")
    else:
        # sigterm mode completes fit() normally, so "killed" proves nothing:
        # the injector's own fired flag is the evidence the grace-window
        # path was exercised (e.g. an at_step past the last boundary would
        # otherwise produce a clean run and a misleading downstream failure)
        assert doomed_fault.fired, (
            f"FaultInjector(sigterm, {phase}, step {at_step}) never fired — "
            f"the drill tested nothing (at_step past the last boundary?)")
    # the drain-on-teardown contract: whatever save was in flight when the
    # fault hit must have committed — a resumable checkpoint exists
    from neuronx_distributed_training_tpu.trainer.elastic import (
        discover_checkpoint_dir,
        read_latest_manifest,
    )
    from neuronx_distributed_training_tpu.config.loader import load_config

    ck_dir = discover_checkpoint_dir(load_config(drill_cfg))
    assert ck_dir is not None, "no checkpoint survived the injected fault"
    manifest = read_latest_manifest(ck_dir)
    assert manifest is not None, (
        f"checkpoint under {ck_dir} has no topology manifest — "
        f"world-size-agnostic resume is broken")
    assert int(manifest["world_size"]) == world, manifest

    # 3. resume — on the (possibly different) world; replan when it changed.
    # phase="restore": the fault rides the FIRST resume incarnation (kill
    # dies mid-restore, sigterm is a notice landing mid-restore) and a
    # second, clean resume proves the save survived and the run continues.
    replan_world = resume_world if resume_world != world else None
    replanned, record = False, None
    if phase == "restore":
        # at_step=0: fire on the first restore, whatever step it resumes
        restore_fault = FaultInjector(at_step=0, mode=mode, phase="restore")
        faulted = run_segment(
            drill_cfg, devices[:resume_world], fault=restore_fault,
            replan_world=replan_world)
        replanned, record = faulted["replanned"], faulted["record"]
        assert restore_fault.fired, (
            "FaultInjector(restore) never fired on the resume incarnation — "
            "the drill tested nothing")
        if mode == "kill":
            assert faulted["killed"], (
                f"FaultInjector(kill, restore, step 0) never fired on the "
                f"resume incarnation — the drill tested nothing")
            # a kill mid-restore (checkpoint read, nothing applied) must
            # leave the save untouched and still resumable
            m2 = read_latest_manifest(ck_dir)
            assert m2 is not None and int(m2["step"]) == int(
                manifest["step"]), (
                f"mid-restore kill corrupted the checkpoint: manifest "
                f"{manifest.get('step')} -> {m2 and m2.get('step')}")
        else:
            assert faulted.get("metrics") is not None, (
                "sigterm-mode restore drill produced no metrics")
    resumed = run_segment(drill_cfg, devices[:resume_world],
                          replan_world=replan_world)
    assert resumed.get("metrics"), "resumed run produced no metrics"
    replanned = replanned or resumed["replanned"]
    record = resumed["record"] or record
    if resume_world != world:
        assert replanned, (
            f"world changed {world} -> {resume_world} but no replan happened")

    # 4. loss-trajectory continuity: every step the resumed run trained must
    # match the control at pinned tolerance (identical synthetic batches,
    # different dp reduction grouping)
    control_losses = read_losses(control["run_dir"])
    drill_losses = read_losses(resumed["run_dir"])
    common = sorted(set(control_losses) & set(drill_losses))
    assert common and max(common) == total_steps, (
        f"resumed run never reached step {total_steps}: "
        f"control={sorted(control_losses)}, drill={sorted(drill_losses)}")
    worst = max(abs(control_losses[s] - drill_losses[s]) for s in common)
    assert worst <= loss_tol, (
        f"loss trajectory diverged after resume: max |Δloss|={worst:.3e} "
        f"> {loss_tol:.0e} over steps {common}")

    # 5. state equivalence at the horizon: bitwise at the same world size,
    # pinned tolerance across a reshard
    params_diff = _tree_max_diff(control["trainer"].params,
                                 resumed["trainer"].params)
    if resume_world == world and not replanned:
        assert params_diff == 0.0, (
            f"same-world resume must be bitwise: max param diff {params_diff:.3e}")
    else:
        assert params_diff <= loss_tol, (
            f"cross-world resume params diverged: max diff {params_diff:.3e}")

    # 6. the restart cost is accounted: run_summary.json carries the elastic
    # trail + goodput breakdown for the resumed incarnation
    summary = {}
    summary_path = Path(resumed["run_dir"]) / "run_summary.json"
    if summary_path.exists():
        summary = json.loads(summary_path.read_text())
    elastic_sec = dict(summary.get("elastic") or {})
    goodput = dict(summary.get("goodput") or {})
    assert elastic_sec.get("resumed"), (
        f"run_summary.json has no elastic resume trail: {summary_path}")
    restart_cost = (float(elastic_sec.get("restart_seconds", 0.0))
                    + float(elastic_sec.get("replan_seconds", 0.0)))
    import time

    report = {
        "ok": True,
        # stamp the drill like bench.py stamps last_measured.json — a stale
        # drill riding later bench lines must be recognizable as stale
        "date": time.strftime("%Y-%m-%d %H:%M:%S"),
        "at_step": at_step, "phase": phase, "mode": mode,
        "world": world, "resume_world": resume_world,
        "total_steps": total_steps,
        "resume_step": int(manifest.get("step", -1)),
        "replanned": replanned,
        "old_plan": (record or {}).get("old_plan"),
        "new_plan": (record or {}).get("new_plan"),
        "max_loss_diff": worst,
        "max_param_diff": params_diff,
        "loss_tol": loss_tol,
        "restart_cost_seconds": round(restart_cost, 3),
        "goodput_fraction": goodput.get("goodput_fraction"),
        "run_dir": str(resumed["run_dir"]),
    }
    if record_path:
        os.makedirs(os.path.dirname(record_path) or ".", exist_ok=True)
        with open(record_path, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    return report


def run_corruption_drill(workdir: str | Path, *, kind: str = "byte_flip",
                         world: int = 4, resume_world: Optional[int] = 2,
                         total_steps: int = 6, save_every: int = 2,
                         loss_tol: float = DEFAULT_LOSS_TOL) -> dict[str, Any]:
    """The corruption drill (docs/elasticity.md "Integrity & walk-back"):
    complete a run, deliberately corrupt its NEWEST checkpoint with ``kind``
    (byte-flip / truncate / delete-item / stale-sidecar), then auto-resume —
    on a different world size when ``resume_world`` differs, so the replan
    path is exercised too — and prove, with no human intervention:

    - the corrupt step is detected, quarantined (renamed + ledger entry),
      and walked past;
    - the restored step is the newest GOOD one, and the elastic replan keys
      off the RESTORED step's manifest, not the corrupt latest;
    - the resumed loss trajectory matches the control at pinned tolerance;
    - the ``integrity`` trail lands in ``run_summary.json``.
    """
    import jax

    from neuronx_distributed_training_tpu.checkpoint import (
        inject_corruption,
    )
    from neuronx_distributed_training_tpu.checkpoint.integrity import (
        parse_quarantine_name,
        read_ledger,
    )
    from neuronx_distributed_training_tpu.config.loader import load_config
    from neuronx_distributed_training_tpu.trainer.elastic import (
        discover_checkpoint_dir,
    )

    devices = jax.devices()
    resume_world = int(resume_world if resume_world is not None else world)
    if max(world, resume_world) > len(devices):
        raise ValueError(
            f"drill wants {max(world, resume_world)} devices, "
            f"have {len(devices)}")
    workdir = Path(workdir)

    # 1. control: uninterrupted run at the original world size
    control = run_segment(
        tiny_llama_config(workdir / "control", max_steps=total_steps,
                          save_every=save_every),
        devices[:world])
    assert control.get("metrics"), "control run produced no metrics"

    # 2. the victim: a CLEAN completed run — the corruption hits the store
    # after commit (bitrot / truncated upload), not the process
    drill_cfg = tiny_llama_config(workdir / "drill", max_steps=total_steps,
                                  save_every=save_every)
    victim = run_segment(drill_cfg, devices[:world])
    assert victim.get("metrics"), "victim run produced no metrics"
    ck_dir = discover_checkpoint_dir(load_config(drill_cfg))
    assert ck_dir is not None, "victim run left no checkpoint"
    steps = sorted(int(p.name) for p in ck_dir.iterdir() if p.name.isdigit())
    assert len(steps) >= 2, (
        f"corruption drill needs >= 2 retained steps to walk back over, "
        f"got {steps}")
    corrupted_step, expect_step = steps[-1], steps[-2]
    what = inject_corruption(ck_dir, corrupted_step, kind)
    logger.info("corruption drill: %s", what)

    # 3. auto-resume on the (possibly different) world — discovery must
    # verify, quarantine the corrupt newest, and key the replan off the
    # step actually restored
    replan_world = resume_world if resume_world != world else None
    resumed = run_segment(drill_cfg, devices[:resume_world],
                          replan_world=replan_world)
    assert resumed.get("metrics"), "resumed run produced no metrics"
    record = resumed["record"]
    if resume_world != world:
        assert resumed["replanned"], (
            f"world changed {world} -> {resume_world} but no replan happened")
        assert int(record["checkpoint_step"]) == expect_step, (
            f"replan keyed off step {record['checkpoint_step']}, not the "
            f"verified step {expect_step} — the replanned layout would chase "
            f"the corrupt latest")

    # 4. quarantine really happened: renamed dir + ledger entry, and the
    # corrupt step is invisible to discovery
    qnames = [p.name for p in ck_dir.iterdir()
              if parse_quarantine_name(p.name) == corrupted_step]
    assert qnames, (
        f"corrupt step {corrupted_step} was not quarantined "
        f"(dir contents: {sorted(p.name for p in ck_dir.iterdir())})")
    ledger_steps = [e.get("step") for e in read_ledger(ck_dir)]
    assert corrupted_step in ledger_steps, (
        f"quarantine ledger has no entry for step {corrupted_step}: "
        f"{ledger_steps}")
    # NOTE a fresh, healthy `<corrupted_step>` dir legitimately reappears:
    # the resumed run retrains through that step and saves it again — the
    # quarantined corpse and the new save coexist

    # 5. the integrity trail is in run_summary.json and names the facts
    summary_path = Path(resumed["run_dir"]) / "run_summary.json"
    summary = (json.loads(summary_path.read_text())
               if summary_path.exists() else {})
    trail = dict(summary.get("integrity") or {})
    assert int(trail.get("verified_step", -1)) == expect_step, trail
    assert int(trail.get("walk_back_count", 0)) >= 1, trail
    assert corrupted_step in (trail.get("quarantined_steps") or []), trail

    # 6. loss-trajectory continuity: the steps retrained after the walk-back
    # must match the control at pinned tolerance
    control_losses = read_losses(control["run_dir"])
    drill_losses = read_losses(resumed["run_dir"])
    common = sorted(set(control_losses) & set(drill_losses))
    assert common and max(common) == total_steps, (
        f"resumed run never reached step {total_steps}: "
        f"control={sorted(control_losses)}, drill={sorted(drill_losses)}")
    worst = max(abs(control_losses[s] - drill_losses[s]) for s in common)
    # same-world walk-back retrains from a bitwise-identical state over
    # identical synthetic batches -> bitwise; cross-dp re-reduces -> pinned
    tol = 0.0 if resume_world == world else loss_tol
    assert worst <= tol, (
        f"loss trajectory diverged after corruption walk-back: "
        f"max |Δloss|={worst:.3e} > {tol:.0e} over steps {common}")

    import time

    return {
        "ok": True,
        "date": time.strftime("%Y-%m-%d %H:%M:%S"),
        "kind": kind,
        "what": what,
        "world": world, "resume_world": resume_world,
        "corrupted_step": corrupted_step,
        "resume_step": expect_step,
        "walked_back": int(trail.get("walk_back_count", 0)),
        "quarantined": trail.get("quarantined_steps"),
        "replanned": bool(resumed["replanned"]),
        "max_loss_diff": worst,
        "loss_tol": loss_tol,
        "run_dir": str(resumed["run_dir"]),
    }


def control_drill_config(workdir: str | Path, *, max_steps: int = 6,
                         save_every: int = 2, log_every: int = 1,
                         alerts: Optional[list] = None,
                         watchdog_seconds: float = 0.0) -> dict[str, Any]:
    """The control drill's tiny-llama config: the elastic drill config plus
    the fleet control plane (consensus control word), the fleet beacon
    plane (dying final beacons), and — for the hang leg — the armed hang
    watchdog.  Synchronous checkpointing: the hang leg ``os._exit``\\ s, so
    the last good save must already be committed, not in flight."""
    cfg = tiny_llama_config(workdir, max_steps=max_steps,
                            save_every=save_every)
    cfg["trainer"]["log_every_n_steps"] = log_every
    cfg["exp_manager"]["checkpoint_callback_params"][
        "async_checkpointing"] = False
    tel = cfg["exp_manager"]["telemetry"]
    tel["control"] = {"enabled": True}
    tel["fleet"] = {"enabled": True, "stale_after_seconds": 300.0}
    if alerts:
        tel["alerts"] = alerts
    if watchdog_seconds > 0:
        tel["health"] = {"watchdog_timeout_seconds": watchdog_seconds,
                         "watchdog_abort": False}
    return cfg


def run_control_drill(workdir: str | Path, *, world: int = 4,
                      total_steps: int = 6, save_every: int = 2,
                      hang_timeout_seconds: float = 240.0) -> dict[str, Any]:
    """The fleet-control acceptance drill (docs/observability.md "Fleet
    control") — the two ISSUE scenarios on the virtual CPU mesh:

    **Consensus stop** — an ``action: halt`` alert firing on ONE simulated
    host's non-replicated metric (``data_wait``, a span only that host
    times) must stop ALL hosts at the same deterministic boundary with a
    drained emergency save and the stop reason in ``run_summary.json``.
    Three legs: the host where the alert fires locally; a second simulated
    host that sees ONLY the folded control word (the ``peer_words`` seam)
    and must stop at the same boundary step with source ``fleet``; and the
    resumed incarnation proving loss-trajectory continuity to the control
    run.

    **Collective-hang escape** — a subprocess incarnation whose boundary
    sync hangs (``FaultInjector(mode="hang", phase="sync")`` — the dead
    peer mid-collective) must exit with the tagged ``EXIT_HANG_ESCAPE``
    code within the watchdog timeout, leaving the ``hang_<step>/`` bundle,
    a dying final beacon, and the control-trail exit note; the restarted
    incarnation resumes from the last good save with loss continuity.
    """
    import subprocess

    import jax

    from neuronx_distributed_training_tpu.config.loader import load_config
    from neuronx_distributed_training_tpu.trainer.control import (
        CONDITION_BITS,
        EXIT_ALERT_HALT,
        EXIT_HANG_ESCAPE,
        exit_code_for_stop,
    )

    devices = jax.devices()
    if world > len(devices):
        raise ValueError(f"drill wants {world} devices, have {len(devices)}")
    workdir = Path(workdir)
    halt_alert = [{"metric": "data_wait", "threshold": 1e-12,
                   "action": "halt", "name": "dw"}]

    # 0. control: an uninterrupted run for the continuity bar
    control = run_segment(
        tiny_llama_config(workdir / "control", max_steps=total_steps,
                          save_every=save_every),
        devices[:world])
    assert control.get("metrics"), "control run produced no metrics"
    control_losses = read_losses(control["run_dir"])

    # 1a. consensus stop, deciding host: the alert fires on THIS host's
    # non-replicated data_wait span; the stop folds through the control
    # word and takes the drained emergency save at the same boundary
    local_cfg = control_drill_config(workdir / "consensus",
                                     max_steps=total_steps,
                                     save_every=save_every,
                                     alerts=halt_alert)
    local = run_segment(local_cfg, devices[:world])
    t = local["trainer"]
    assert t.stop_class == "alert_halt", t.stop_class
    assert exit_code_for_stop(t.stop_class) == EXIT_ALERT_HALT
    stop_step = int(t.step)
    rs = json.loads(
        (Path(local["run_dir"]) / "run_summary.json").read_text())
    assert rs["elastic"]["stop_reason"].startswith("alert dw:"), rs["elastic"]
    assert rs["elastic"]["stop_class"] == "alert_halt", rs["elastic"]
    decisions = rs["control"]["decisions"]
    assert decisions and decisions[-1]["conditions"] == ["alert_halt"], (
        decisions)
    assert decisions[-1]["step"] == stop_step and decisions[-1]["stop"], (
        decisions)
    ck_dir = Path(local["run_dir"]) / "checkpoints"
    assert str(stop_step) in {p.name for p in ck_dir.iterdir()}, (
        f"no drained emergency save at stop step {stop_step}: "
        f"{sorted(p.name for p in ck_dir.iterdir())}")

    # 1b. consensus stop, OTHER host: no local condition at all — only the
    # folded control word (peer_words stands in for the deciding host's
    # contribution).  Must stop at the SAME deterministic boundary step,
    # with an emergency save and the honest "fleet consensus" reason.
    peer_cfg = control_drill_config(workdir / "peer", max_steps=total_steps,
                                    save_every=save_every)
    peer = run_segment(peer_cfg, devices[:world],
                       peer_words=lambda: CONDITION_BITS["alert_halt"])
    pt = peer["trainer"]
    assert int(pt.step) == stop_step, (
        f"peer host stopped at step {pt.step}, deciding host at "
        f"{stop_step} — NOT the same boundary")
    prs = json.loads(
        (Path(peer["run_dir"]) / "run_summary.json").read_text())
    assert prs["elastic"]["stop_reason"].startswith("fleet consensus:"), (
        prs["elastic"])
    pdec = prs["control"]["decisions"][-1]
    assert pdec["source"] == "fleet" and pdec["step"] == stop_step, pdec
    pck = Path(peer["run_dir"]) / "checkpoints"
    assert str(stop_step) in {p.name for p in pck.iterdir()}, (
        "peer host took no emergency save")

    # 1c. the resumed incarnation (alert disarmed — the operator fixed the
    # condition) continues from the emergency save to the horizon with
    # loss-trajectory continuity vs the uninterrupted control
    resume_cfg = control_drill_config(workdir / "consensus",
                                      max_steps=total_steps,
                                      save_every=save_every)
    resumed = run_segment(resume_cfg, devices[:world])
    assert resumed.get("metrics"), "resumed run produced no metrics"
    drill_losses = read_losses(resumed["run_dir"])
    common = sorted(set(control_losses) & set(drill_losses))
    assert common and max(common) == total_steps, (
        f"resumed run never reached step {total_steps}: "
        f"{sorted(drill_losses)}")
    worst = max(abs(control_losses[s] - drill_losses[s]) for s in common)
    assert worst == 0.0, (
        f"same-world consensus resume must be bitwise: max |Δloss| "
        f"{worst:.3e} over steps {common}")

    # 2. collective-hang escape: the doomed incarnation runs in a CHILD
    # process (the escape is a real os._exit) with its boundary sync hung
    # at step 4 — the watchdog must exit EXIT_HANG_ESCAPE well before the
    # injected 60 s sleep ends
    hang_cfg = control_drill_config(workdir / "hang", max_steps=total_steps,
                                    save_every=save_every,
                                    watchdog_seconds=2.0)
    cfg_path = workdir / "hang_cfg.json"
    cfg_path.write_text(json.dumps(hang_cfg))
    child = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--hang-child",
         str(cfg_path), "--world", str(world), "--at-step", "4"],
        timeout=hang_timeout_seconds, capture_output=True, text=True,
    )
    assert child.returncode == EXIT_HANG_ESCAPE, (
        f"hung incarnation exited {child.returncode}, want "
        f"EXIT_HANG_ESCAPE={EXIT_HANG_ESCAPE}\n--- child stderr ---\n"
        + child.stderr[-2000:])
    hang_run = _run_dir(load_config(hang_cfg))
    bundles = sorted(p.name for p in hang_run.glob("hang_*"))
    assert bundles, f"no hang_<step>/ bundle in {hang_run}"
    beacons = [json.loads(l) for l in
               (hang_run / "fleet" / "host_0.jsonl").read_text().splitlines()]
    assert beacons and "hang escape" in str(
        beacons[-1].get("last_exception")), (
        f"final beacon is not a dying one: {beacons[-1]}")
    hrs = json.loads((hang_run / "run_summary.json").read_text())
    hdec = hrs["control"]["decisions"][-1]
    assert hdec["conditions"] == ["hang_escape"] and hdec.get("exit"), hdec

    # 3. the restarted incarnation resumes from the last good save and
    # finishes with loss continuity — the orchestrator's restart IS the
    # recovery, exactly as elastic resume promises
    hang_resumed = run_segment(
        control_drill_config(workdir / "hang", max_steps=total_steps,
                             save_every=save_every),
        devices[:world])
    assert hang_resumed.get("metrics"), "hang-resumed run has no metrics"
    hlosses = read_losses(hang_resumed["run_dir"])
    hcommon = sorted(set(control_losses) & set(hlosses))
    assert hcommon and max(hcommon) == total_steps, sorted(hlosses)
    hworst = max(abs(control_losses[s] - hlosses[s]) for s in hcommon)
    assert hworst == 0.0, (
        f"post-hang-escape resume diverged: max |Δloss| {hworst:.3e}")

    import time

    return {
        "ok": True,
        "date": time.strftime("%Y-%m-%d %H:%M:%S"),
        "world": world,
        "total_steps": total_steps,
        "consensus_stop_step": stop_step,
        "consensus_sources": ["local", "fleet"],
        "hang_escape_code": int(child.returncode),
        "hang_bundle": bundles[0],
        "max_loss_diff": max(worst, hworst),
        "run_dir": str(resumed["run_dir"]),
    }


def _hang_child(cfg_path: str, world: int, at_step: int) -> int:
    """The doomed incarnation of the hang leg (runs in a subprocess): its
    boundary sync blocks via ``FaultInjector(mode="hang", phase="sync")``;
    the armed watchdog must dump, beacon, and ``os._exit(EXIT_HANG_ESCAPE)``
    — so reaching the end of this function is itself a drill failure."""
    import jax

    from neuronx_distributed_training_tpu.trainer.elastic import FaultInjector

    raw = json.loads(Path(cfg_path).read_text())
    fault = FaultInjector(at_step=at_step, mode="hang", phase="sync",
                          hang_seconds=60.0)
    run_segment(raw, jax.devices()[:world], fault=fault)
    logger.error("hang child SURVIVED the hung sync — watchdog escape "
                 "did not fire")
    return 3


def main(argv: Optional[list[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="CI gate: the canonical dp 4 -> 2 kill drill PLUS "
                         "a byte-flip corruption leg in a temp dir (single "
                         "process, virtual CPU devices)")
    ap.add_argument("--control-smoke", action="store_true",
                    help="fleet-control acceptance drill (docs/observability"
                         ".md 'Fleet control'): a halt alert on ONE "
                         "simulated host's non-replicated metric stops all "
                         "hosts at the same step with a drained emergency "
                         "save, and a hung boundary sync exits the process "
                         "with the tagged EXIT_HANG_ESCAPE code before "
                         "resuming cleanly")
    ap.add_argument("--hang-child", default=None, metavar="CFG_JSON",
                    help=argparse.SUPPRESS)  # internal: the hang leg's
    #                                          subprocess incarnation
    ap.add_argument("--corrupt", default=None, metavar="KIND",
                    help="run the corruption drill instead of the fault "
                         "drill: corrupt the completed run's newest "
                         "checkpoint with KIND (byte_flip/truncate/"
                         "delete_item/stale_sidecar) and prove quarantine + "
                         "walk-back + replan-off-the-verified-step")
    ap.add_argument("--at-step", type=int, default=3)
    ap.add_argument("--phase", choices=["step", "save", "restore"],
                    default="step")
    ap.add_argument("--mode", choices=["kill", "sigterm"], default="kill")
    ap.add_argument("--world", type=int, default=4,
                    help="device count of the original run")
    ap.add_argument("--resume-world", type=int, default=2,
                    help="device count after the 'preemption' (different "
                         "value triggers the restart-time replan)")
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--save-every", type=int, default=2)
    ap.add_argument("--loss-tol", type=float, default=DEFAULT_LOSS_TOL)
    ap.add_argument("--workdir", default=None,
                    help="drill working dir (default: a fresh temp dir)")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the drill report as JSON ('-' = stdout, "
                         "last line, tools/_jsonout contract)")
    ap.add_argument("--no-record", action="store_true",
                    help=f"do not refresh {LAST_DRILL_PATH}")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    # force the 8-device virtual CPU platform BEFORE jax initializes devices
    # (same dance as tests/conftest.py — sitecustomize may have imported jax)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    if args.hang_child is not None:
        return _hang_child(args.hang_child, args.world, args.at_step)

    workdir = args.workdir
    if workdir is None:
        import tempfile

        workdir = tempfile.mkdtemp(prefix="nxdt_elastic_drill_")
    record_path = None if args.no_record else os.path.normpath(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", LAST_DRILL_PATH))
    try:
        if args.control_smoke:
            # no --loss-tol here: every control-drill leg resumes at the
            # SAME world size, so the continuity bar is bitwise
            report = run_control_drill(
                workdir, world=args.world, total_steps=args.steps,
                save_every=args.save_every,
            )
        elif args.corrupt is not None:
            report = run_corruption_drill(
                workdir, kind=args.corrupt,
                world=args.world, resume_world=args.resume_world,
                total_steps=args.steps, save_every=args.save_every,
                loss_tol=args.loss_tol,
            )
        else:
            report = run_drill(
                workdir,
                at_step=args.at_step, phase=args.phase, mode=args.mode,
                world=args.world, resume_world=args.resume_world,
                total_steps=args.steps, save_every=args.save_every,
                loss_tol=args.loss_tol,
                record_path=record_path,
            )
            if args.smoke:
                # the --smoke CI gate grows a corruption leg: newest step
                # byte-flipped, auto-resume must quarantine + walk back +
                # replan off the verified step (docs/elasticity.md)
                corruption = run_corruption_drill(
                    Path(workdir) / "corruption", kind="byte_flip",
                    world=args.world, resume_world=args.resume_world,
                    total_steps=args.steps, save_every=args.save_every,
                    loss_tol=args.loss_tol,
                )
                report["integrity"] = {
                    k: corruption.get(k)
                    for k in ("kind", "corrupted_step", "resume_step",
                              "walked_back", "max_loss_diff")
                }
                if record_path:
                    with open(record_path, "w") as f:
                        json.dump(report, f, indent=1)
                        f.write("\n")
    except AssertionError as e:
        logger.error("drill FAILED: %s", e)
        if args.json:
            from _jsonout import write_json

            write_json({"ok": False, "error": str(e)}, args.json)
        return 1
    if args.control_smoke:
        logger.info(
            "control drill OK: consensus stop at step %d on both simulated "
            "hosts (sources %s), hang escape exited %d with bundle %s, "
            "resumed to step %d bitwise (max |Δloss| %.1e)",
            report["consensus_stop_step"], report["consensus_sources"],
            report["hang_escape_code"], report["hang_bundle"],
            report["total_steps"], report["max_loss_diff"],
        )
    elif args.corrupt is not None:
        logger.info(
            "corruption drill OK (%s): step %d corrupted -> quarantined, "
            "resumed %d -> %d devices from step %d (walked back %d); "
            "max |Δloss| %.2e",
            report["kind"], report["corrupted_step"], report["world"],
            report["resume_world"], report["resume_step"],
            report["walked_back"], report["max_loss_diff"],
        )
    else:
        logger.info(
            "drill OK: killed at step %d (%s/%s), resumed %d -> %d devices "
            "from step %d; max |Δloss| %.2e, restart cost %.2fs, goodput %.4f",
            report["at_step"], report["mode"], report["phase"], report["world"],
            report["resume_world"], report["resume_step"],
            report["max_loss_diff"], report["restart_cost_seconds"],
            report["goodput_fraction"] or 0.0,
        )
        if args.smoke and report.get("integrity"):
            logger.info(
                "corruption leg OK: %s at step %s -> walked back to %s",
                report["integrity"]["kind"],
                report["integrity"]["corrupted_step"],
                report["integrity"]["resume_step"],
            )
    if args.json:
        from _jsonout import write_json

        write_json(report, args.json)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
