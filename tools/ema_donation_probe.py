"""Minimal on-device repro for the EMA opt-state donation INVALID_ARGUMENT.

Round-2 observation (tunnelled TPU runtime): jitting the train step with
``donate_argnums=(0, 1)`` fails with INVALID_ARGUMENT when the opt state
carries the ``ema`` tree; donate-nothing and plain jit run clean.  A CPU
repro attempt (round 3) found no params<->ema buffer aliasing, so the root
cause sits in the TPU runtime's donation path, not in our pytrees.

Run ON DEVICE (needs the axon TPU):
    PYTHONPATH=/root/repo:$PYTHONPATH python tools/ema_donation_probe.py

Prints one line per donation mode: ok / INVALID_ARGUMENT.  If "all" passes,
remove the narrowed ``donate="params"`` workaround in trainer/loop.py.
"""

import jax
import jax.numpy as jnp


def main() -> None:
    print("backend:", jax.default_backend(), jax.devices())
    params = {"w": jnp.ones((512, 512), jnp.float32)}
    opt = {
        "mu": jax.tree_util.tree_map(jnp.zeros_like, params),
        "nu": jax.tree_util.tree_map(jnp.zeros_like, params),
        "ema": jax.tree_util.tree_map(lambda x: x * 1.0, params),
        "step": jnp.zeros((), jnp.int32),
    }

    def step(p, s):
        g = jax.tree_util.tree_map(lambda x: x * 0.01, p)
        mu = jax.tree_util.tree_map(lambda m, gg: 0.9 * m + gg, s["mu"], g)
        nu = jax.tree_util.tree_map(lambda n, gg: 0.99 * n + gg * gg, s["nu"], g)
        newp = jax.tree_util.tree_map(lambda x, m: x - 1e-3 * m, p, mu)
        ema = jax.tree_util.tree_map(
            lambda e, x: 0.99 * e + 0.01 * x, s["ema"], newp)
        return newp, {"mu": mu, "nu": nu, "ema": ema, "step": s["step"] + 1}

    for mode, argnums in (("none", ()), ("params", (0,)), ("all", (0, 1))):
        try:
            f = jax.jit(step, donate_argnums=argnums)
            p2, s2 = f(jax.tree_util.tree_map(jnp.copy, params),
                       jax.tree_util.tree_map(jnp.copy, opt))
            # value fetch forces completion on the tunnelled backend
            print(f"donate={mode}: ok (psum={float(jnp.sum(p2['w'])):.3f})")
        except Exception as e:
            print(f"donate={mode}: {type(e).__name__}: {str(e)[:200]}")


if __name__ == "__main__":
    main()
