#!/usr/bin/env python
"""Fold per-host fleet beacons into a terminal report (and fleet_summary.json).

The cross-host answer to "which host is slow, which host is stalling data,
which host went quiet" — everything the beacon plane (``telemetry.fleet``,
docs/observability.md "Fleet observability") wrote, from one terminal:

    python tools/fleet_monitor.py nxdt_experiments/hf_llama3_8B/version_0
    python tools/fleet_monitor.py path/to/run_dir/fleet        # beacon dir
    python tools/fleet_monitor.py run_dir --json -             # last line = JSON
    python tools/fleet_monitor.py run_dir --write              # refresh
                                                               # fleet_summary.json
    python tools/fleet_monitor.py run_dir --live               # quiet-host
                                                               # check vs NOW

Accepts a run dir (aggregates its ``fleet/`` beacon files), a beacon
directory itself, or an already-written ``fleet_summary.json`` (rendered
as-is).  ``--json`` goes through the shared ``tools/_jsonout.py``
single-last-line contract.  Offline aggregation anchors quiet-host
detection to the fleet's newest beacon (a file set copied off a dead fleet
must not report every host quiet); ``--live`` anchors it to the wall clock
instead, for watching a running fleet.

Stdlib-only: ``telemetry/fleet.py`` is loaded by file path, so this runs on
a login node with nothing installed (the same posture as
``tools/metrics_report.py``).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # tools/_jsonout

from _jsonout import write_json  # noqa: E402


def _load_fleet_module():
    """``telemetry/fleet.py`` by file path — stdlib-only by design, so the
    package (and jax) never has to be importable here."""
    path = (Path(__file__).resolve().parent.parent
            / "neuronx_distributed_training_tpu" / "telemetry" / "fleet.py")
    spec = importlib.util.spec_from_file_location("_nxdt_fleet", path)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves string annotations through sys.modules[module]:
    # register BEFORE exec or every @dataclass in the file blows up
    sys.modules["_nxdt_fleet"] = mod
    spec.loader.exec_module(mod)
    return mod


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if v != v:
            return "nan"
        a = abs(v)
        if a != 0 and (a >= 1e6 or a < 1e-3):
            return f"{v:.3e}"
        return f"{v:.4f}" if a < 100 else f"{v:,.1f}"
    return str(v)


def _table(rows: list[tuple], header: tuple) -> str:
    widths = [max(len(str(r[i])) for r in [header, *rows])
              for i in range(len(header))]

    def fmt_row(r):
        return "  ".join(str(c).ljust(w) if i == 0 else str(c).rjust(w)
                         for i, (c, w) in enumerate(zip(r, widths)))

    sep = "  ".join("-" * w for w in widths)
    return "\n".join([fmt_row(header), sep, *(fmt_row(r) for r in rows)])


def resolve_input(path: str) -> tuple[str, str | None]:
    """``(kind, resolved)`` — kind is ``fleet_dir`` / ``summary`` / None."""
    if os.path.isdir(path):
        fleet = os.path.join(path, "fleet")
        if os.path.isdir(fleet):
            return "fleet_dir", fleet
        if any(f.startswith("host_") and f.endswith(".jsonl")
               for f in os.listdir(path)):
            return "fleet_dir", path
        summary = os.path.join(path, "fleet_summary.json")
        if os.path.exists(summary):
            return "summary", summary
        return "none", None
    if os.path.exists(path):
        return "summary", path
    return "none", None


def control_trail_section(run_dir: str | None) -> str:
    """The fleet-control trail from the run dir's ``run_summary.json``
    (trainer.control, docs/observability.md "Fleet control"): operator
    commands with ack status and every consensus stop/checkpoint decision —
    rendered next to the alert firings so the "why did the fleet stop"
    answer sits beside the "which host was slow" one."""
    if not run_dir:
        return ""
    try:
        with open(os.path.join(run_dir, "run_summary.json")) as f:
            summary = json.load(f)
    except (OSError, ValueError):
        return ""
    parts: list[str] = []
    alerts = summary.get("alerts") or []
    if alerts:
        lines = [f"alerts ({len(alerts)} firing"
                 f"{'s' if len(alerts) != 1 else ''}):"]
        for a in alerts:
            if isinstance(a, dict):
                lines.append(f"  step {str(a.get('step', '?')):<7} "
                             f"action={str(a.get('action', '?')):<5} "
                             f"[{a.get('rule', '?')}] {a.get('message', '')}")
        parts.append("\n".join(lines))
    ctl = summary.get("control")
    if isinstance(ctl, dict) and ctl:
        from _ctltrail import control_trail_lines

        parts.append("\n".join(
            ["fleet control (consensus decisions — docs/observability.md"
             " 'Fleet control'):", *control_trail_lines(ctl)]))
    return "\n\n".join(parts)


def render(summary: dict) -> str:
    parts: list[str] = []
    n = summary.get("n_hosts", 0)
    parts.append(f"fleet: {n} host{'s' if n != 1 else ''} "
                 f"(stale_after={_fmt(summary.get('stale_after_seconds'))}s "
                 f"— docs/observability.md 'Fleet observability')")

    hosts = summary.get("hosts") or {}
    if hosts:
        rows = []
        quiet = {q["host"] for q in summary.get("quiet_hosts") or []}
        for hid in sorted(hosts, key=lambda h: int(h)):
            h = hosts[hid]
            status = ("QUIET" if int(hid) in quiet
                      else "died" if h.get("last_exception")
                      else "closed" if h.get("closed") else "live")
            rows.append((hid, h.get("last_step"), h.get("beacons"), status,
                         _fmt(h.get("step_time")), _fmt(h.get("mfu")),
                         _fmt(h.get("goodput_fraction")),
                         _fmt(h.get("data_wait_seconds"))))
        parts.append(_table(rows, ("host", "step", "beacons", "status",
                                   "step_time", "mfu", "goodput",
                                   "data_wait_s")))

    st = summary.get("straggler")
    if st:
        parts.append(
            f"straggler: host {st['host']} led {st['windows_led']}/"
            f"{st['windows_attributed']} attributed windows "
            f"(of {st['windows_total']}) — dominant cause: {st['cause']}")
    windows = summary.get("windows") or []
    if windows:
        rows = [(w["step"], _fmt(w.get("arrival_skew_seconds")),
                 w.get("straggler_host") if w.get("straggler_host") is not None
                 else "-",
                 w.get("cause") or "balanced",
                 _fmt(w.get("straggler_excess_seconds")))
                for w in windows[-10:]]
        parts.append("recent windows (straggler = busiest host; cause from "
                     "its own span deltas):")
        parts.append(_table(rows, ("step", "skew_s", "straggler", "cause",
                                   "excess_s")))

    spread = summary.get("spread") or {}
    if spread:
        rows = []
        for k in sorted(spread):
            s = spread[k]
            rows.append((k,
                         f"{_fmt(s['min']['value'])} (host {s['min']['host']})",
                         _fmt(s["p50"]),
                         f"{_fmt(s['max']['value'])} (host {s['max']['host']})"))
        parts.append("per-host spread:")
        parts.append(_table(rows, ("metric", "min", "p50", "max")))

    hosts_mem = [(hid, h) for hid, h in sorted(
        (summary.get("hosts") or {}).items(), key=lambda kv: int(kv[0]))
        if h.get("peak_hbm_bytes") is not None
        or h.get("hbm_headroom_fraction") is not None]
    if hosts_mem:
        rows = []
        for hid, h in hosts_mem:
            peak = h.get("peak_hbm_bytes")
            head = h.get("hbm_headroom_fraction")
            rows.append((hid,
                         f"{peak / 1024**3:.3f}G" if peak else "-",
                         f"{100 * head:.1f}%" if head is not None else "-"))
        parts.append("per-host memory (telemetry.memory beacons — worst "
                     "device watermark + remaining headroom; "
                     "tools/memory_report.py renders the attribution):")
        parts.append(_table(rows, ("host", "peak_hbm", "headroom")))

    gp = summary.get("goodput")
    if gp:
        parts.append(
            f"fleet goodput: {_fmt(gp.get('fleet_goodput_fraction'))} "
            f"(worst: host {gp.get('worst_host')}) = 1 - common overhead "
            f"{_fmt(gp.get('common_overhead_fraction'))} - straggler loss "
            f"{_fmt(gp.get('straggler_loss_fraction'))} "
            f"(best: host {gp.get('best_host')})")

    for f in summary.get("findings") or []:
        parts.append(f"FINDING [{f.get('kind')}] {f.get('message')}")
    if not summary.get("findings"):
        parts.append("no findings (no quiet or dead hosts)")
    return "\n\n".join(parts)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="run dir (with fleet/), a beacon dir, or a "
                                 "fleet_summary.json")
    ap.add_argument("--stale-after", type=float, default=600.0,
                    help="quiet-host threshold seconds (default 600)")
    ap.add_argument("--max-windows", type=int, default=64,
                    help="skew windows retained (default 64)")
    ap.add_argument("--live", action="store_true",
                    help="anchor quiet-host detection to the wall clock "
                         "(watching a RUNNING fleet) instead of the newest "
                         "beacon (offline analysis)")
    ap.add_argument("--write", action="store_true",
                    help="also write/refresh fleet_summary.json next to the "
                         "beacon dir")
    ap.add_argument("--json", metavar="PATH",
                    help="write the summary as JSON ('-' = stdout, last "
                         "line, the shared tools/_jsonout contract)")
    args = ap.parse_args(argv)

    kind, resolved = resolve_input(args.path)
    if kind == "none":
        print(f"fleet_monitor: no fleet beacons or summary at {args.path}",
              file=sys.stderr)
        return 2
    if kind == "summary":
        with open(resolved) as f:
            try:
                summary = json.load(f)
            except ValueError as e:
                print(f"fleet_monitor: unreadable {resolved}: {e}",
                      file=sys.stderr)
                return 2
    else:
        fleet = _load_fleet_module()
        summary = fleet.aggregate_fleet(
            resolved, stale_after_seconds=args.stale_after,
            max_windows=args.max_windows,
            now=time.time() if args.live else None)
        if args.write:
            out = os.path.join(os.path.dirname(resolved.rstrip("/")) or ".",
                               "fleet_summary.json")
            # THE atomic writer (serialize-first + temp/fsync/rename) —
            # fleet.py inlines it stdlib-only exactly so tools can share it
            fleet.write_fleet_summary(summary, out)
            print(f"wrote {out}", file=sys.stderr)

    print(render(summary))
    # the control/alert trail lives in run_summary.json one level above the
    # beacon dir (or in the run dir itself) — render it next to the fleet
    # findings so stop decisions and straggler attribution read together
    if os.path.isdir(args.path):
        run_dir = (args.path if kind != "fleet_dir"
                   or os.path.basename(resolved.rstrip("/")) != "fleet"
                   else os.path.dirname(resolved.rstrip("/")) or ".")
        trail = control_trail_section(run_dir)
        if trail:
            print()
            print(trail)
    if args.json:
        write_json(summary, args.json)
    return 1 if summary.get("findings") else 0


if __name__ == "__main__":
    raise SystemExit(main())
