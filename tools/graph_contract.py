#!/usr/bin/env python
"""Graph-contract CLI — the compile-artifact regression ratchet.

For each config this AOT-lowers the (shrunk) train step on abstract inputs,
extracts its **contract fingerprint** (collective census by kind×axis-group
with per-collective provenance, donation coverage map, ``memory_analysis()``
bytes, matmul dtype census) and compares it against the committed golden
snapshot under ``neuronx_distributed_training_tpu/analysis/contracts/``:

    python tools/graph_contract.py --check --all-examples
    python tools/graph_contract.py --check --config examples/conf/foo.yaml
    python tools/graph_contract.py --update-contracts --all-examples
    python tools/graph_contract.py --update-contracts --config foo.yaml \
        --justify "added fused CE: +2 tp all-reduces"

``--check`` fails (exit 1) on any regression: a collective class that grew,
a GSPMD-inserted reshard no declared source explains, a donated leaf that
lost its alias, a matmul dtype upcast, or resident bytes beyond tolerance —
each explained in config-level terms naming the offending HLO op
(docs/static_analysis.md "Graph contracts").

``--update-contracts`` is the ratchet's write side: shrinking fingerprints
commit silently; GROWTH refuses to commit without ``--justify`` (recorded
in-file), and unattributed collectives become named waivers.

``--jobs N`` fingerprints configs in parallel processes (the sweep is
embarrassingly parallel); output order stays deterministic and ``--json``
keeps the shared single-last-line contract (tools/_jsonout.py).
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # tools/ siblings


def _fingerprint_worker(args: tuple) -> dict:
    """One config -> fingerprint dict (or an ``error`` payload).  Runs in a
    worker process under --jobs: the parent exported XLA_FLAGS/JAX_PLATFORMS
    before the pool spawned, so each worker sizes its own CPU world."""
    path, shrink, platform = args
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from neuronx_distributed_training_tpu.analysis.graph_contract import (
        ContractError,
        fingerprint_config,
    )

    try:
        return {"path": path, "fingerprint": fingerprint_config(
            path, shrink=shrink)}
    except ContractError as e:
        return {"path": path, "error": str(e)}
    except Exception as e:  # noqa: BLE001 — a worker must return, not die
        return {"path": path, "error": f"{type(e).__name__}: {e}"}


def main() -> None:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--config", action="append", default=[],
                    help="YAML config to fingerprint (repeatable)")
    ap.add_argument("--all-examples", action="store_true",
                    help="every examples/conf/*.yaml")
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--check", action="store_true", default=True,
                      help="diff against the committed contract (default)")
    mode.add_argument("--update-contracts", action="store_true",
                      help="rewrite the committed snapshot(s); growth "
                           "requires --justify")
    ap.add_argument("--justify", metavar="TEXT",
                    help="in-file justification for contract growth "
                         "(--update-contracts)")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="fingerprint N configs in parallel processes "
                         "(default 1: serial)")
    ap.add_argument("--no-shrink", dest="shrink", action="store_false",
                    help="fingerprint at true config size (needs a device "
                         "world that large)")
    ap.add_argument("--memory-tolerance", type=float, default=None,
                    help="resident-bytes growth fraction that fails "
                         "(default 0.10)")
    ap.add_argument("--contracts-dir", metavar="DIR",
                    help="snapshot directory override (default: the "
                         "committed analysis/contracts/)")
    ap.add_argument("--json", metavar="PATH",
                    help="machine-readable report ('-' for stdout, "
                         "guaranteed last line)")
    ap.add_argument("--platform", default="cpu", choices=["cpu", "tpu"],
                    help="jax platform for the abstract lowering (default "
                         "cpu: the check is static)")
    args = ap.parse_args()

    configs = list(args.config)
    if args.all_examples:
        import glob

        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        configs += sorted(glob.glob(os.path.join(here, "examples/conf/*.yaml")))
    if not configs:
        ap.error("nothing to do: pass --config and/or --all-examples")

    # Size the virtual device world BEFORE any jax initializes (parent or
    # --jobs workers — the env is inherited across the spawn).
    if args.platform == "cpu":
        from preflight_audit import _required_world

        world = max(_required_world(configs, args.shrink), 8)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={world}"
            ).strip()
        # exported (not setdefault): spawned --jobs workers must come up on
        # CPU even when the parent env pins a TPU plugin platform
        os.environ["JAX_PLATFORMS"] = "cpu"

    work = [(p, args.shrink, args.platform) for p in configs]
    if args.jobs > 1 and len(work) > 1:
        import multiprocessing as mp
        from concurrent.futures import ProcessPoolExecutor

        with ProcessPoolExecutor(
                max_workers=min(args.jobs, len(work)),
                mp_context=mp.get_context("spawn")) as pool:
            results = list(pool.map(_fingerprint_worker, work))
    else:
        results = [_fingerprint_worker(w) for w in work]

    from neuronx_distributed_training_tpu.analysis import graph_contract as gc
    from neuronx_distributed_training_tpu.analysis.report import AuditReport

    tol = (args.memory_tolerance if args.memory_tolerance is not None
           else gc.MEMORY_TOLERANCE)
    cdir = Path(args.contracts_dir) if args.contracts_dir else None
    failed = False
    out: dict = {"reports": []}
    for res in results:  # input order: deterministic merged output
        name = Path(res["path"]).name
        if "error" in res:
            rep = AuditReport(config=name)
            rep.add("GC000", "error", res["error"],
                    hint="the config lowers no further; fix it before "
                         "contracting")
            print(rep.format())
            print()
            out["reports"].append(rep.to_dict())
            failed = True
            continue
        fp = res["fingerprint"]
        if args.update_contracts:
            try:
                path, rep = gc.update_contract(
                    res["path"], fp, justify=args.justify,
                    memory_tolerance=tol, contracts_dir=cdir)
                drift = rep.by_severity() or "no drift"
                print(f"contract [{name}]: updated -> {path} ({drift})")
            except gc.ContractError as e:
                print(f"contract [{name}]: REFUSED: {e}")
                failed = True
                out["reports"].append(
                    {"config": name, "verdict": "error",
                     "refused": str(e)})
                continue
        else:
            rep = gc.check_contract(res["path"], fp,
                                    memory_tolerance=tol,
                                    contracts_dir=cdir)
            verdict = rep.worst() or "clean"
            unattr = sum(v["count"] for v in
                         gc.unattributed_entries(fp).values())
            total = sum(v["count"] for v in
                        (fp.get("collectives") or {}).values())
            print(f"contract [{name}]: {verdict} "
                  f"({total} collectives, {total - unattr} attributed)")
            if rep.findings:
                print(rep.format())
            print()
            failed |= rep.failed("error")
        rep_dict = rep.to_dict()
        rep_dict["fingerprint"] = fp
        out["reports"].append(rep_dict)

    if args.json:
        from _jsonout import write_json

        write_json(out, args.json)

    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
