"""On-chip revalidation of the Mosaic-compiled Pallas kernels (round-5 queue).

The flash-attention kernel last executed on REAL TPU in round 2; segment
masking (round 3) and every later change has only run in Pallas interpret
mode on the CPU mesh.  This script runs the compiled kernel on the tunnelled
chip at real tile sizes and compares against the pure-XLA reference
(``ops.attention_reference``) — fwd AND bwd, across the variant matrix:
causal, GQA, segment-masked (packed sequences), sliding-window.

The ring body (``parallel/ring_attention.py``) needs a multi-device ring and
cannot execute on the single tunnelled chip; its on-chip story remains the
flash kernel it calls per-shard, which IS covered here.  Records one JSON
line per case to ``bench_results/kernel_reval_r5.json``.

Run ON DEVICE (the axon TPU is the one client — connection discipline per
bench_results/r4_notes.md):
    PYTHONPATH=/root/repo:$PYTHONPATH python tools/kernel_revalidation.py

Reference parity target: the NKI kernels the reference trusts in production
(reference ``modeling_llama.py:482-489``).
"""

from __future__ import annotations

import json
import os
import sys
import time
import zlib

import jax
import jax.numpy as jnp
import numpy as np


def _ref_attention(q, k, v, *, causal, segment_ids=None, window=None):
    """Pure-XLA reference (fp32 accumulation) mirroring ops/flash_attention
    semantics: [b, s, h, d] layout, GQA by head-group mapping, optional
    segments and window."""
    qh, kh = q.shape[2], k.shape[2]
    group = qh // kh
    k = jnp.repeat(k, group, axis=2)
    v = jnp.repeat(v, group, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s *= 1.0 / np.sqrt(q.shape[-1])
    sq, sk = q.shape[1], k.shape[1]
    mask = jnp.ones((sq, sk), bool)
    if causal:
        mask &= jnp.tril(jnp.ones((sq, sk), bool))
    if window is not None:
        idx = jnp.arange(sq)[:, None] - jnp.arange(sk)[None, :]
        mask &= idx < window
    mask = mask[None, None]
    if segment_ids is not None:
        seg = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        mask = mask & seg
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


def run_case(name: str, *, b, qh, kh, s, d, causal, segments, window,
             block_q, block_kv) -> dict:
    from neuronx_distributed_training_tpu.ops import flash_attention as fa

    # crc32, not hash(): str hash is randomized per process (PYTHONHASHSEED),
    # so a failing case would get fresh data on the rerun and not reproduce
    key = jax.random.PRNGKey(zlib.crc32(name.encode()) % (2**31))
    kq, kk, kv_, _ = jax.random.split(key, 4)
    q = jax.random.normal(kq, (b, s, qh, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, s, kh, d), jnp.bfloat16)
    v = jax.random.normal(kv_, (b, s, kh, d), jnp.bfloat16)
    seg_ids = None
    if segments:
        # two packed documents per row, split at a non-tile-aligned boundary
        cut = s // 2 + 37
        seg_ids = jnp.where(jnp.arange(s) < cut, 0, 1)[None, :].repeat(b, 0)

    win = window

    def flash_loss(q, k, v):
        o = fa.flash_attention(
            q, k, v, causal=causal, segment_ids=seg_ids,
            sliding_window=win, block_q=block_q, block_kv=block_kv,
        )
        return jnp.sum(o.astype(jnp.float32) ** 2), o

    def ref_loss(q, k, v):
        o = _ref_attention(q, k, v, causal=causal, segment_ids=seg_ids,
                           window=win)
        return jnp.sum(o.astype(jnp.float32) ** 2), o

    t0 = time.perf_counter()
    (gf, of) = jax.jit(jax.value_and_grad(flash_loss, argnums=(0, 1, 2),
                                          has_aux=True))(q, k, v)
    jax.block_until_ready(gf)
    t_flash = time.perf_counter() - t0
    (gr, orf) = jax.jit(jax.value_and_grad(ref_loss, argnums=(0, 1, 2),
                                           has_aux=True))(q, k, v)
    jax.block_until_ready(gr)

    (lf, o_f), grads_f = gf, of
    (lr, o_r), grads_r = gr, orf

    def rel(a, b):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        return float(np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-9))

    fwd_err = rel(o_f, o_r)
    bwd_err = max(rel(a, b) for a, b in zip(grads_f, grads_r))
    ok = fwd_err < 2e-2 and bwd_err < 5e-2  # bf16 kernel vs fp32-accum ref
    return {
        "case": name, "ok": bool(ok), "fwd_rel_err": round(fwd_err, 5),
        "bwd_rel_err": round(bwd_err, 5), "compile_plus_run_s": round(t_flash, 2),
        "block_q": block_q, "block_kv": block_kv, "shape": [b, qh, kh, s, d],
    }


def main() -> None:
    smoke = "--smoke" in sys.argv  # tiny shapes: CPU/interpret plumbing check
    dev = jax.devices()[0]
    print(f"kernel_reval: device {dev.platform} {dev.device_kind}", file=sys.stderr)
    on_tpu = dev.platform == "tpu"
    if smoke:
        cases = [
            dict(name="causal_gqa", b=1, qh=4, kh=2, s=256, d=64, causal=True,
                 segments=False, window=None, block_q=128, block_kv=128),
            dict(name="segment_masked", b=1, qh=2, kh=2, s=256, d=64,
                 causal=True, segments=True, window=None, block_q=128,
                 block_kv=128),
        ]
    else:
        cases = [
            dict(name="causal_mha", b=1, qh=8, kh=8, s=4096, d=128, causal=True,
                 segments=False, window=None, block_q=512, block_kv=2048),
            dict(name="causal_gqa", b=1, qh=32, kh=8, s=4096, d=128, causal=True,
                 segments=False, window=None, block_q=512, block_kv=2048),
            dict(name="segment_masked", b=1, qh=8, kh=8, s=4096, d=128,
                 causal=True, segments=True, window=None, block_q=512,
                 block_kv=2048),
            dict(name="sliding_window", b=1, qh=8, kh=8, s=4096, d=128,
                 causal=True, segments=False, window=1024, block_q=512,
                 block_kv=1024),
        ]
    out = []
    for c in cases:
        name = c.pop("name")
        try:
            r = run_case(name, **c)
        except Exception as e:  # noqa: BLE001 — record, keep going
            r = {"case": name, "ok": False,
                 "error": f"{type(e).__name__}: {str(e)[:400]}"}
        print(json.dumps(r), file=sys.stderr)
        out.append(r)
    base = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "bench_results")
    os.makedirs(base, exist_ok=True)
    payload = {
        "date": time.strftime("%Y-%m-%d %H:%M:%S"),
        "device": dev.device_kind, "platform": dev.platform,
        "on_tpu": on_tpu, "cases": out,
        "all_ok": all(r.get("ok") for r in out),
    }
    fname = "kernel_reval_smoke.json" if smoke else "kernel_reval_r5.json"
    with open(os.path.join(base, fname), "w") as f:
        json.dump(payload, f, indent=1)
    print(json.dumps({"kernel_reval_all_ok": payload["all_ok"]}))


if __name__ == "__main__":
    main()
