#!/usr/bin/env python
"""Render memory observability artifacts as terminal tables.

The reader for everything ``telemetry.memory`` writes
(docs/observability.md "Memory observability"):

    python tools/memory_report.py nxdt_experiments/run/version_0   # run dir
    python tools/memory_report.py path/to/memory_summary.json
    python tools/memory_report.py path/to/oom_00000042             # OOM bundle
    python tools/memory_report.py capture.pprof                    # raw profile
    python tools/memory_report.py run_dir --json -                 # last line
                                                                   # = JSON

Shows the live-buffer attribution table (per subsystem, with the honest
``unattributed`` remainder), the exact tree bytes of the state subsystems,
per-device spread, headroom, and — when the summary carries the planner's
predicted breakdown — the predicted-vs-measured table the
``plan.py --calibrate-from`` ratios come from.  An OOM bundle renders its
attribution-at-death and the allocator-sample ring.

Stdlib-only: a raw ``.pprof`` input loads ``telemetry/memory.py`` by file
path (its parser is deliberately dependency-free), so this runs on a login
node with nothing installed — the ``metrics_report``/``fleet_monitor``
posture.  ``--json`` keeps the shared ``tools/_jsonout.py``
single-last-line contract.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # tools/_jsonout

from _jsonout import write_json  # noqa: E402


def _load_memory_module():
    """``telemetry/memory.py`` by file path — stdlib-only at import by
    design, so the package (and jax) never has to be importable here."""
    path = (Path(__file__).resolve().parent.parent
            / "neuronx_distributed_training_tpu" / "telemetry" / "memory.py")
    spec = importlib.util.spec_from_file_location("_nxdt_memory", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_nxdt_memory"] = mod
    spec.loader.exec_module(mod)
    return mod


def _mb(v) -> str:
    if v is None:
        return "-"
    try:
        return f"{float(v) / 1024**2:,.2f}"
    except (TypeError, ValueError):
        return "-"


def _table(rows: list[tuple], header: tuple) -> str:
    widths = [max(len(str(r[i])) for r in [header, *rows])
              for i in range(len(header))]

    def fmt_row(r):
        return "  ".join(str(c).ljust(w) if i == 0 else str(c).rjust(w)
                         for i, (c, w) in enumerate(zip(r, widths)))

    sep = "  ".join("-" * w for w in widths)
    return "\n".join([fmt_row(header), sep, *(fmt_row(r) for r in rows)])


def attribution_rows(attribution: dict, total) -> list[tuple]:
    # render order comes from the plane itself (telemetry.memory.SUBSYSTEMS
    # via the file-path load) — a class this tool's source predates must
    # still get a full row, never a silent drop
    order = tuple(_load_memory_module().SUBSYSTEMS)
    rows = []
    for cls in (*order, *(c for c in attribution if c not in order)):
        rec = attribution.get(cls)
        if rec is None:
            continue
        b = rec.get("bytes") if isinstance(rec, dict) else rec
        c = rec.get("count") if isinstance(rec, dict) else None
        pct = (f"{100 * float(b) / float(total):.1f}%"
               if total and b is not None else "-")
        rows.append((cls, _mb(b), pct, c if c is not None else "-"))
    return rows


def render_summary(summary: dict) -> str:
    parts: list[str] = []
    prof = summary.get("profile") or {}
    total = prof.get("total_bytes")
    parts.append(
        f"memory summary (schema {summary.get('schema', '?')}): profile at "
        f"step {summary.get('profiled_step', '?')} — "
        f"{_mb(total)} MB live across "
        f"{prof.get('num_devices', '?')} device(s), "
        f"{prof.get('num_samples', '?')} allocation sites "
        f"(docs/observability.md 'Memory observability')")

    att = summary.get("attribution") or {}
    if att:
        parts.append("attribution (live bytes per subsystem; the total "
                     "reconciles with the profile by construction):")
        parts.append(_table(attribution_rows(att, total),
                            ("subsystem", "MB", "share", "allocs")))

    tree = summary.get("tree_bytes") or {}
    if tree:
        rows = [(k, _mb(v)) for k, v in sorted(tree.items())]
        parts.append("exact tree bytes (host-side sharding metadata — the "
                     "truth for state the profile's stacks can't see past "
                     "donation):")
        parts.append(_table(rows, ("subtree", "MB")))

    by_dev = prof.get("by_device") or {}
    if len(by_dev) > 1:
        vals = sorted(by_dev.items(), key=lambda kv: -float(kv[1]))
        rows = [(d, _mb(b)) for d, b in vals]
        parts.append("per-device live bytes (spread — a skewed stage shows "
                     "here):")
        parts.append(_table(rows, ("device", "MB")))

    sampled = summary.get("sampled") or {}
    per_dev = sampled.get("per_device") or []
    if per_dev:
        rows = []
        for s in per_dev:
            limit = s.get("bytes_limit")
            head = (f"{100 * (1 - s.get('bytes_in_use', 0) / limit):.1f}%"
                    if limit else "-")
            rows.append((s.get("device"), _mb(s.get("bytes_in_use")),
                         _mb(s.get("peak_bytes_in_use")), _mb(limit), head))
        parts.append("allocator samples (at capture):")
        parts.append(_table(rows, ("device", "in_use_MB", "peak_MB",
                                   "limit_MB", "headroom")))
    if sampled.get("peak_hbm_bytes"):
        parts.append(f"running peak HBM (worst device watermark): "
                     f"{_mb(sampled['peak_hbm_bytes'])} MB")

    predicted = summary.get("predicted") or {}
    if predicted:
        # THE shared measured-side join (telemetry.memory.
        # measured_hbm_categories — file-path-loaded, stdlib-only): the
        # table must show the very numbers plan.py --calibrate-from
        # applies, not a hand-maintained copy of the map
        mem = _load_memory_module()
        measured_cat, peak = mem.measured_hbm_categories(summary)
        rows = []
        for cat in sorted(predicted):
            if cat == "total":
                continue
            pred = predicted[cat]
            meas = measured_cat.get(cat)
            ratio = (f"{meas / pred:.2f}" if meas and pred else "-")
            rows.append((cat, _mb(pred), _mb(meas), ratio))
        ptot = predicted.get("total")
        rows.append(("total (vs peak)", _mb(ptot), _mb(peak),
                     f"{peak / ptot:.2f}" if peak and ptot else "-"))
        parts.append("predicted vs measured, per device (the planner's HBM "
                     "model audited — feed back with tools/plan.py "
                     "--calibrate-from memory_summary.json):")
        parts.append(_table(rows, ("category", "predicted_MB", "measured_MB",
                                   "ratio")))
    return "\n\n".join(parts)


def render_oom(bundle: dict, ring: list) -> str:
    parts = [f"OOM bundle: step {bundle.get('step', '?')} — "
             f"{bundle.get('error', '')[:200]}"]
    att = bundle.get("attribution_at_death") or bundle.get("attribution")
    total = bundle.get("in_use_bytes_at_death")
    if att:
        parts.append("attribution at death:")
        parts.append(_table(attribution_rows(att, total),
                            ("subsystem", "MB", "share", "allocs")))
    tree = bundle.get("tree_bytes") or {}
    if tree:
        parts.append(_table([(k, _mb(v)) for k, v in sorted(tree.items())],
                            ("subtree", "MB")))
    pred = bundle.get("predicted_hbm_breakdown") or {}
    if pred:
        rows = [(k, _mb(v)) for k, v in sorted(pred.items())]
        parts.append("planner's predicted per-device breakdown (the "
                     "predicted-vs-actual pair in one artifact):")
        parts.append(_table(rows, ("category", "MB")))
    ma = bundle.get("memory_analysis") or {}
    if ma.get("peak_bytes"):
        parts.append(f"compile-census memory_analysis peak: "
                     f"{_mb(ma['peak_bytes'])} MB")
    if bundle.get("peak_hbm_bytes"):
        parts.append(f"sampled peak HBM before death: "
                     f"{_mb(bundle['peak_hbm_bytes'])} MB")
    if ring:
        rows = []
        for rec in ring[-8:]:
            devs = rec.get("devices") or []
            in_use = [d.get("bytes_in_use", 0) for d in devs]
            rows.append((rec.get("step"), len(devs),
                         _mb(max(in_use) if in_use else None)))
        parts.append("last allocator samples (the ring):")
        parts.append(_table(rows, ("step", "devices", "max_in_use_MB")))
    return "\n\n".join(parts)


def render_profile(profile: dict, attribution: dict) -> str:
    total = profile.get("total_bytes")
    parts = [f"raw memory profile: {_mb(total)} MB live, "
             f"{len(profile.get('samples') or [])} allocation sites, "
             f"{len(profile.get('by_device') or {})} device(s)"]
    parts.append(_table(attribution_rows(attribution, total),
                        ("subsystem", "MB", "share", "allocs")))
    by_dev = profile.get("by_device") or {}
    if by_dev:
        parts.append(_table(sorted(((d, _mb(b)) for d, b in by_dev.items())),
                            ("device", "MB")))
    return "\n\n".join(parts)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="run dir, memory_summary.json, an "
                                 "oom_<step>/ bundle dir, or a raw .pprof "
                                 "capture")
    ap.add_argument("--json", metavar="PATH",
                    help="write the parsed payload as JSON ('-' = stdout, "
                         "last line, the shared tools/_jsonout contract)")
    args = ap.parse_args(argv)

    path = Path(args.path)
    payload: dict
    if path.is_dir():
        oom_json = path / "oom.json"
        if oom_json.exists():
            with open(oom_json) as f:
                bundle = json.load(f)
            ring = []
            try:
                with open(path / "samples.json") as f:
                    ring = json.load(f)
            except (OSError, ValueError):
                pass
            print(render_oom(bundle, ring))
            payload = {"kind": "oom", **bundle, "ring_length": len(ring)}
        else:
            summary_path = path / "memory_summary.json"
            if not summary_path.exists():
                print(f"memory_report: no memory_summary.json or oom.json "
                      f"under {path}", file=sys.stderr)
                return 2
            with open(summary_path) as f:
                summary = json.load(f)
            print(render_summary(summary))
            payload = summary
    elif path.suffix == ".json":
        with open(path) as f:
            doc = json.load(f)
        if doc.get("kind") == "oom":
            print(render_oom(doc, []))
        else:
            print(render_summary(doc))
        payload = doc
    else:
        # raw pprof capture: parse + attribute stdlib-only
        mem = _load_memory_module()
        data = path.read_bytes()
        profile = mem.parse_memory_profile(data)
        attribution = mem.attribute_profile(profile)
        print(render_profile(profile, attribution))
        payload = {
            "kind": "profile",
            "total_bytes": profile["total_bytes"],
            "total_count": profile["total_count"],
            "num_samples": len(profile["samples"]),
            "by_device": profile["by_device"],
            "attribution": attribution,
        }
    if args.json:
        write_json(payload, args.json)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
