#!/usr/bin/env python
"""Render a run's ``metrics.jsonl`` + ``run_summary.json`` as a terminal table.

The quick ocular check before reaching for TensorBoard: last/mean/peak per
logged metric, the goodput breakdown, and the compile census — everything the
unified telemetry layer wrote, in one screen.

    python tools/metrics_report.py nxdt_experiments/hf_llama3_8B/version_0
    python tools/metrics_report.py path/to/metrics.jsonl --last 50
    python tools/metrics_report.py run_dir --follow --interval 5

``--follow`` live-tails a RUNNING fleet from one terminal: the report
re-renders every ``--interval`` seconds, picking up new metrics.jsonl
lines, the latest ``fleet_summary.json`` (straggler / quiet-host findings
from the beacon plane, docs/observability.md "Fleet observability"), and a
per-host beacon freshness line tailed straight from ``fleet/host_*.jsonl``.
Stop with Ctrl-C.

Pure stdlib on purpose: it must run on a login node with nothing installed.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

# sibling helpers (tools/_ctltrail.py): running as a script puts this dir
# on sys.path already; a by-file-path spec load (the tests) does not
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def load_metrics(path: str) -> list[dict]:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue  # torn tail line from a live run
    return records


def _fmt(v: float) -> str:
    if v != v:  # NaN
        return "nan"
    a = abs(v)
    if a != 0 and (a >= 1e6 or a < 1e-3):
        return f"{v:.3e}"
    if a >= 100 or float(v).is_integer():
        return f"{v:,.1f}" if not float(v).is_integer() else f"{v:,.0f}"
    return f"{v:.4f}"


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n:.0f} B"
        n /= 1024
    return f"{n:.1f} TiB"


def _table(rows: list[tuple[str, ...]], header: tuple[str, ...]) -> str:
    widths = [max(len(str(r[i])) for r in [header, *rows])
              for i in range(len(header))]
    def fmt_row(r):
        return "  ".join(str(c).ljust(w) if i == 0 else str(c).rjust(w)
                         for i, (c, w) in enumerate(zip(r, widths)))
    sep = "  ".join("-" * w for w in widths)
    return "\n".join([fmt_row(header), sep, *(fmt_row(r) for r in rows)])


def metrics_table(records: list[dict], last_n: int = 0) -> str:
    if last_n > 0:
        records = records[-last_n:]
    by_key: dict[str, list[float]] = {}
    for rec in records:
        for k, v in rec.items():
            if k == "step" or not isinstance(v, (int, float)):
                continue
            if isinstance(v, float) and math.isnan(v):
                continue
            by_key.setdefault(k, []).append(float(v))
    rows = []
    for k in sorted(by_key):
        vals = by_key[k]
        rows.append((k, _fmt(vals[-1]), _fmt(sum(vals) / len(vals)),
                     _fmt(max(vals)), str(len(vals))))
    steps = [r.get("step") for r in records if isinstance(r.get("step"), int)]
    span = f"steps {steps[0]}..{steps[-1]}" if steps else "no steps"
    return (f"metrics ({span}, {len(records)} boundary records)\n"
            + _table(rows, ("metric", "last", "mean", "peak", "n")))


def goodput_section(summary: dict) -> str:
    gp = summary.get("goodput")
    if not gp:
        return ""
    lines = ["", "goodput"]
    frac = gp.get("goodput_fraction")
    if frac is not None:
        lines.append(f"  goodput_fraction      {frac:.4f}")
    for key in ("wall_seconds", "productive_seconds", "nonproductive_seconds"):
        if key in gp:
            lines.append(f"  {key:<21} {_fmt(gp[key])}")
    for name, secs in sorted((gp.get("breakdown_seconds") or {}).items()):
        lines.append(f"    {name:<19} {_fmt(secs)} s")
    return "\n".join(lines)


def _plan_str(plan: dict) -> str:
    # deliberate copy of trainer/elastic.py::_plan_str — importing it would
    # pull the package __init__ (and jax) into this stdlib-only tool; keep
    # the two in sync when the plan record grows a rendered key
    keys = ("dp", "tp", "pp", "cp", "ep", "vp")
    parts = [f"{k}={plan[k]}" for k in keys if plan.get(k) is not None]
    if plan.get("micro_batch_size") is not None:
        parts.append(f"mbs={plan['micro_batch_size']}")
    if plan.get("schedule") not in (None, "none"):
        parts.append(f"sched={plan['schedule']}")
    return " ".join(parts) or "?"


def elastic_section(summary: dict) -> str:
    """Restart/replan trail (trainer.elastic -> run_summary.json "elastic"):
    whether this incarnation resumed, what the restart cost in span time,
    and — when the world size changed — the old plan -> new plan record the
    restart-time autotune replanner imposed (docs/elasticity.md)."""
    el = summary.get("elastic")
    if not isinstance(el, dict) or not el:
        return ""
    lines = ["", "elastic (restart/replan trail — docs/elasticity.md)"]
    lines.append(f"  resumed               {bool(el.get('resumed'))}")
    for key in ("restart_seconds", "replan_seconds"):
        if el.get(key) is not None:
            lines.append(f"  {key:<21} {_fmt(el[key])}")
    if el.get("stop_reason"):
        lines.append(f"  stop_reason           {el['stop_reason']}")
    rec = el.get("replan")
    if isinstance(rec, dict) and rec:
        lines.append(
            f"  replanned             world "
            f"{rec.get('old_world', '?')} -> {rec.get('new_world', '?')} "
            f"chips (resuming step {rec.get('checkpoint_step', '?')})")
        lines.append(f"    old plan            "
                     f"{_plan_str(rec.get('old_plan') or {})}")
        lines.append(f"    new plan            "
                     f"{_plan_str(rec.get('new_plan') or {})}")
        if rec.get("predicted_step_seconds") is not None:
            lines.append(f"    predicted_step      "
                         f"{_fmt(rec['predicted_step_seconds'])} s")
        if rec.get("skipped_incompatible"):
            lines.append(f"    skipped             "
                         f"{rec['skipped_incompatible']} layout-incompatible "
                         f"candidate(s)")
    return "\n".join(lines)


def integrity_section(summary: dict) -> str:
    """Checkpoint-integrity trail (checkpoint/integrity.py ->
    run_summary.json "integrity"): which step actually verified at restore,
    how many corrupt steps the walk-back skipped, what was quarantined, and
    the post-commit audit's cost (docs/elasticity.md "Integrity &
    walk-back")."""
    it = summary.get("integrity")
    if not isinstance(it, dict) or not it:
        return ""
    lines = ["", "integrity (verified restore — docs/elasticity.md)"]
    if it.get("verified_step") is not None:
        lines.append(f"  verified_step         {it['verified_step']}")
    if it.get("walk_back_count") is not None:
        lines.append(f"  walk_back_count       {it['walk_back_count']}")
    q = it.get("quarantined_steps") or []
    if q:
        lines.append(f"  quarantined_steps     "
                     f"{', '.join(str(s) for s in q)}")
    if it.get("legacy_restore"):
        lines.append("  legacy_restore        True (pre-integrity "
                     "checkpoint, restored UNVERIFIED)")
    if it.get("verify_seconds") is not None:
        lines.append(f"  verify_seconds        {_fmt(it['verify_seconds'])}")
    audit = it.get("audit")
    if isinstance(audit, dict) and audit:
        line = (f"  audit                 {audit.get('audited', 0)} step(s), "
                f"{audit.get('failed', 0)} failed, "
                f"{_fmt(audit.get('seconds', 0.0))} s")
        if audit.get("incomplete"):
            line += f", {audit['incomplete']} incomplete at teardown"
        lines.append(line)
        aq = it.get("audit_quarantined") or []
        if aq:
            lines.append(f"    audit_quarantined   "
                         f"{', '.join(str(s) for s in aq)}")
    return "\n".join(lines)


def anomalies_section(summary: dict) -> str:
    """Flight-recorder trail: one line per forensic bundle the run dumped
    (render a bundle itself with ``tools/anomaly_report.py``)."""
    anomalies = summary.get("anomalies") or []
    if not anomalies:
        return ""
    lines = ["", f"anomalies ({len(anomalies)} forensic bundle"
                 f"{'s' if len(anomalies) != 1 else ''} — "
                 f"tools/anomaly_report.py renders one)"]
    for a in anomalies:
        # tolerate partial/malformed entries (older schema / hand edits) —
        # a bad trail line must not abort the whole report
        if not isinstance(a, dict):
            lines.append(f"  (unreadable entry: {a!r})")
            continue
        step = str(a.get("step", "?"))
        policy = str(a.get("policy", "?"))
        lines.append(f"  step {step:<8} policy={policy:<18} "
                     f"{a.get('bundle', '?')}")
    return "\n".join(lines)


def trace_section(trace: dict) -> str:
    """Device-time trace summary (telemetry.trace -> trace_summary.json):
    achieved overlap, exposed collective time, and the top-5 op table —
    render the full breakdown with ``tools/trace_report.py``."""
    if not trace:
        return ""
    lines = ["", "device-time trace (tools/trace_report.py renders the "
                 "full breakdown)"]
    ov = trace.get("achieved_overlap")
    if ov is not None:
        lines.append(f"  achieved_overlap      {100 * float(ov):.1f}% of "
                     f"collective wire time hidden under compute")
    for key in ("collective_seconds", "exposed_collective_seconds",
                "total_device_seconds"):
        if trace.get(key) is not None:
            lines.append(f"  {key:<21} {_fmt(trace[key])}")
    top = (trace.get("top_ops") or [])[:5]
    if top:
        lines.append("  top ops by device time:")
        for o in top:
            lines.append(
                f"    {o.get('op', '?'):<20} {_fmt(o.get('total_seconds', 0))} s"
                f"  ({100 * o.get('share', 0.0):.1f}%, {o.get('class', '?')})")
    return "\n".join(lines)


def provenance_section(summary: dict) -> str:
    """Bench provenance (bench.py acquire_device): the acquire mode, the
    watchdog phase tag actually reached, PJRT handshake timing, and backend
    identity — what makes a dead bench round diagnosable from its JSON
    artifact alone."""
    prov = summary.get("provenance")
    if not isinstance(prov, dict) or not prov:
        return ""
    lines = ["", "bench provenance (acquire/backend forensics)"]
    for key in ("acquire_mode", "connect_phase", "requested_platform",
                "platform", "device_kind", "jax_version",
                "plugin_init_seconds", "first_rpc_seconds",
                "probe_seconds", "probe_attempts",
                "connect_timeout_seconds", "error"):
        if prov.get(key) is not None:
            v = prov[key]
            lines.append(f"  {key:<22} "
                         f"{_fmt(v) if isinstance(v, (int, float)) else v}")
    return "\n".join(lines)


def perf_contract_section(summary: dict) -> str:
    """Perf-contract verdict (analysis.perf_contract): whether this line's
    measured numbers were checked against the committed per-topology
    baseline, and the named PC findings when any fired."""
    pcv = summary.get("perf_contract")
    if not isinstance(pcv, dict) or not pcv:
        return ""
    lines = ["", "perf contract (measured-runtime ratchet — "
                 "docs/observability.md)"]
    lines.append(f"  verdict               {pcv.get('verdict', '?')}"
                 + (f"  (key {pcv['key']})" if pcv.get("key") else ""))
    for f in pcv.get("findings") or []:
        if isinstance(f, dict):
            lines.append(f"    {f.get('rule', '?')}: {f.get('message', '')}")
    if pcv.get("error"):
        lines.append(f"  error                 {pcv['error']}")
    return "\n".join(lines)


def comms_section(summary: dict) -> str:
    """In-loop achieved interconnect bandwidth (telemetry.comms — the
    trainer's join of traced per-class wire seconds with the cost model's
    byte volumes; tools/comms_report.py renders the standalone sweep)."""
    comms = summary.get("comms")
    if not isinstance(comms, dict) or not comms.get("classes"):
        return ""
    peak = comms.get("peak_bandwidth_gbps")
    lines = ["", f"interconnect (measured achieved bandwidth vs "
                 f"{_fmt(peak) if peak is not None else '?'} GB/s topology "
                 f"peak — docs/observability.md 'Interconnect observatory')"]
    for kind in sorted(comms["classes"]):
        e = comms["classes"][kind]
        if not isinstance(e, dict):
            continue
        eff = e.get("efficiency")
        lines.append(
            f"  {kind:<20} achieved={_fmt(e.get('achieved_gbps'))} GB/s"
            + (f"  efficiency={100 * eff:.1f}%" if eff is not None else "")
            + (f"  wire_s/step={_fmt(e.get('wire_seconds_per_step'), 6)}"
               if e.get("wire_seconds_per_step") is not None else ""))
    return "\n".join(lines)


def alerts_section(summary: dict) -> str:
    """Alert-engine trail (telemetry.alerts -> run_summary.json "alerts"):
    one line per firing, with the action the loop took."""
    alerts = summary.get("alerts") or []
    if not alerts:
        return ""
    lines = ["", f"alerts ({len(alerts)} firing"
                 f"{'s' if len(alerts) != 1 else ''} — "
                 f"docs/observability.md 'Alert rules')"]
    for a in alerts:
        if not isinstance(a, dict):
            lines.append(f"  (unreadable entry: {a!r})")
            continue
        lines.append(f"  step {str(a.get('step', '?')):<8} "
                     f"action={str(a.get('action', '?')):<5} "
                     f"[{a.get('rule', '?')}] {a.get('message', '')}")
    return "\n".join(lines)


def control_section(summary: dict) -> str:
    """Fleet-control trail (trainer.control -> run_summary.json "control"):
    operator commands received (with ack status), and every consensus
    decision — the step it landed, the deciding condition, and the reason
    (docs/observability.md "Fleet control").  The line formatter is shared
    with ``tools/fleet_monitor.py`` (``tools/_ctltrail.py``)."""
    ctl = summary.get("control")
    if not isinstance(ctl, dict) or not ctl:
        return ""
    from _ctltrail import control_trail_lines

    return "\n".join(["", "fleet control (consensus decisions — "
                          "docs/observability.md 'Fleet control')",
                      *control_trail_lines(ctl)])


def memory_section(summary: dict, run_dir: str | None) -> str:
    """Memory observability (telemetry.memory -> run_summary.json "memory"
    + memory_summary.json): live-buffer attribution per subsystem, peak
    HBM, headroom, and the OOM trail when one fired — render the full
    breakdown (per-device spread, predicted-vs-measured) with
    ``tools/memory_report.py``."""
    mem = summary.get("memory")
    oom = summary.get("oom")
    doc: dict = {}
    if run_dir:
        try:
            with open(os.path.join(run_dir, "memory_summary.json")) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            pass
    if not isinstance(mem, dict):
        mem = {}
    if not mem and not doc and not oom:
        return ""
    lines = ["", "memory (telemetry.memory — docs/observability.md "
                 "'Memory observability'; tools/memory_report.py renders "
                 "the full breakdown)"]
    prof = doc.get("profile") or {}
    in_use = mem.get("in_use_bytes") or prof.get("total_bytes")
    if in_use is not None:
        lines.append(f"  in_use_bytes          {_fmt_bytes(in_use)} "
                     f"(profiled step "
                     f"{mem.get('profiled_step', doc.get('profiled_step', '?'))})")
    peak = mem.get("peak_hbm_bytes") or (doc.get("sampled")
                                         or {}).get("peak_hbm_bytes")
    if peak is not None:
        lines.append(f"  peak_hbm_bytes        {_fmt_bytes(peak)} "
                     f"(worst device watermark)")
    pred = mem.get("predicted_hbm_bytes") or (doc.get("predicted")
                                              or {}).get("total")
    if pred and in_use:
        n_dev = max(int(prof.get("num_devices", 1) or 1), 1)
        lines.append(f"  predicted_hbm_bytes   {_fmt_bytes(pred)} per device "
                     f"(measured/predicted "
                     f"{float(peak or in_use / n_dev) / float(pred):.2f})")
    att = doc.get("attribution") or {}
    if not att and mem.get("attribution"):
        att = {k: {"bytes": v} for k, v in mem["attribution"].items()
               if v is not None}
    if att:
        total = prof.get("total_bytes") or sum(
            (r.get("bytes") if isinstance(r, dict) else r) or 0
            for r in att.values())
        lines.append("  attribution (live bytes per subsystem):")
        order = ("params", "opt_state", "master", "ema", "activations",
                 "chunk_store", "moe_workspace", "batch", "executable",
                 "unattributed")
        # known order first, then any class this tool's list predates —
        # the plane's "never silently dropped" contract holds here too
        for cls in (*order, *(c for c in att if c not in order)):
            rec = att.get(cls)
            if rec is None:
                continue
            b = rec.get("bytes") if isinstance(rec, dict) else rec
            share = (f"  ({100 * float(b) / float(total):.1f}%)"
                     if total and b is not None else "")
            lines.append(f"    {cls:<14} {_fmt_bytes(b or 0):>12}{share}")
    if isinstance(oom, dict) and oom:
        lines.append(f"  OOM at step {oom.get('step', '?')}: bundle "
                     f"{oom.get('bundle', '?')} — {oom.get('error', '')}")
    return "\n".join(lines)


def fleet_section(run_dir: str | None) -> str:
    """Fleet plane summary (telemetry.fleet -> fleet_summary.json): host
    count, the modal straggler with its cause, quiet hosts, and the fleet
    goodput decomposition — render the full per-window breakdown with
    ``tools/fleet_monitor.py``."""
    if not run_dir:
        return ""
    path = os.path.join(run_dir, "fleet_summary.json")
    if not os.path.exists(path):
        return ""
    try:
        with open(path) as f:
            fs = json.load(f)
    except ValueError:
        return f"\nunreadable {path}"
    lines = ["", f"fleet ({fs.get('n_hosts', 0)} hosts — "
                 f"tools/fleet_monitor.py renders the full breakdown)"]
    st = fs.get("straggler")
    if st:
        lines.append(f"  straggler             host {st.get('host')} "
                     f"({st.get('cause')}; led {st.get('windows_led')}/"
                     f"{st.get('windows_attributed')} windows)")
    gp = fs.get("goodput") or {}
    if gp.get("fleet_goodput_fraction") is not None:
        lines.append(f"  fleet_goodput         "
                     f"{_fmt(gp['fleet_goodput_fraction'])} "
                     f"(straggler loss {_fmt(gp.get('straggler_loss_fraction', 0))}, "
                     f"common {_fmt(gp.get('common_overhead_fraction', 0))})")
    for q in fs.get("quiet_hosts") or []:
        lines.append(f"  QUIET host {q.get('host')}    last step "
                     f"{q.get('last_step')}, silent "
                     f"{_fmt(q.get('silent_seconds'))} s")
    for f in fs.get("findings") or []:
        if f.get("kind") != "fleet_stall":  # quiet hosts rendered above
            lines.append(f"  [{f.get('kind')}] {f.get('message')}")
    return "\n".join(lines)


def beacon_tail_section(run_dir: str | None) -> str:
    """Per-host beacon freshness tailed straight from ``fleet/host_*.jsonl``
    (no aggregation — just "who reported what, when", cheap enough for the
    --follow refresh loop).  Torn tail lines (a live writer mid-flush, a
    died host) are skipped."""
    if not run_dir:
        return ""
    fleet_dir = os.path.join(run_dir, "fleet")
    if not os.path.isdir(fleet_dir):
        return ""
    import glob
    import time as _time

    rows = []
    now = _time.time()
    for path in sorted(glob.glob(os.path.join(fleet_dir, "host_*.jsonl"))):
        last = None
        try:
            # only the last record matters: seek to the final few KB
            # instead of re-parsing a multi-day stream on every refresh
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - 8192))
                tail = f.read().decode("utf-8", errors="replace")
            for line in tail.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    last = json.loads(line)
                except ValueError:
                    continue  # the cut-off first line / a torn tail
        except OSError:
            continue
        if not isinstance(last, dict):
            continue
        age = (now - float(last["t_wall"])
               if last.get("t_wall") is not None else None)
        status = ("closed" if last.get("closing")
                  else "DIED" if last.get("last_exception") else "live")
        m = last.get("metrics") or {}
        rows.append((os.path.basename(path).split(".")[0],
                     str(last.get("step", "?")), status,
                     f"{age:.0f}s" if age is not None else "-",
                     _fmt(m["loss"]) if m.get("loss") is not None else "-"))
    if not rows:
        return ""
    return "\n".join(["", "beacons (age = seconds since last heartbeat)",
                      _table(rows, ("host", "step", "status", "age",
                                    "loss"))])


def census_section(summary: dict) -> str:
    lines: list[str] = []
    if "compile_seconds" in summary:
        lines.append(f"  compile_seconds       {_fmt(summary['compile_seconds'])}")
    mem = summary.get("memory_analysis") or {}
    for key in ("peak_bytes", "temp_size_in_bytes", "argument_size_in_bytes",
                "output_size_in_bytes"):
        if key in mem:
            lines.append(f"  {key:<21} {_fmt_bytes(mem[key])}")
    coll = summary.get("collectives") or {}
    nonzero = {k: v for k, v in coll.items() if v}
    if coll:
        lines.append("  collectives           "
                     + (", ".join(f"{k}={v}" for k, v in sorted(nonzero.items()))
                        or "none"))
    for key in ("model_family", "n_chips", "seq_len", "global_batch_size",
                "pipeline_schedule", "bubble_fraction_predicted",
                "bubble_fraction_measured",
                "fwd_flops_per_token",
                "train_step_flops_per_token", "peak_tflops_per_chip"):
        if summary.get(key) is not None:
            v = summary[key]
            lines.append(f"  {key:<21} {_fmt(v) if isinstance(v, (int, float)) else v}")
    ticks = summary.get("pipeline_ticks_per_step")
    if isinstance(ticks, dict) and ticks:
        # the work-compacted executor's per-step trip counts (span +
        # per-kind active ticks vs the old lockstep count)
        lines.append("  ticks_per_step        "
                     + ", ".join(f"{k}={ticks[k]}" for k in sorted(ticks)))
    if summary.get("retrace_events"):
        lines.append(f"  retrace_events        {len(summary['retrace_events'])} "
                     f"(see run_summary.json — each cost a recompile)")
    if not lines:
        return ""
    return "\n".join(["", "compile census / run facts", *lines])


def render(metrics_path: str | None, summary_path: str | None,
           last_n: int = 0, trace_path: str | None = None,
           run_dir: str | None = None) -> str:
    parts: list[str] = []
    if metrics_path and os.path.exists(metrics_path):
        records = load_metrics(metrics_path)
        if records:
            parts.append(metrics_table(records, last_n))
        else:
            parts.append(f"no records in {metrics_path}")
    summary = {}
    if summary_path and os.path.exists(summary_path):
        try:
            with open(summary_path) as f:
                summary = json.load(f)
        except ValueError as e:
            parts.append(f"unreadable {summary_path}: {e}")
    if summary:
        parts.append(goodput_section(summary))
        parts.append(elastic_section(summary))
        parts.append(integrity_section(summary))
        parts.append(anomalies_section(summary))
        parts.append(alerts_section(summary))
        parts.append(control_section(summary))
        parts.append(census_section(summary))
        parts.append(comms_section(summary))
        parts.append(provenance_section(summary))
        parts.append(perf_contract_section(summary))
    parts.append(memory_section(summary, run_dir))
    parts.append(fleet_section(run_dir))
    parts.append(beacon_tail_section(run_dir))
    if trace_path and os.path.exists(trace_path):
        try:
            with open(trace_path) as f:
                parts.append(trace_section(json.load(f)))
        except ValueError as e:
            parts.append(f"unreadable {trace_path}: {e}")
    return "\n".join(p for p in parts if p)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="run dir (containing metrics.jsonl / "
                                 "run_summary.json) or a metrics.jsonl file")
    ap.add_argument("--last", type=int, default=0,
                    help="only the last N boundary records (default: all)")
    ap.add_argument("--follow", action="store_true",
                    help="live-tail: re-render every --interval seconds "
                         "(metrics.jsonl + fleet beacons; Ctrl-C stops)")
    ap.add_argument("--interval", type=float, default=5.0,
                    help="refresh interval seconds for --follow (default 5)")
    ap.add_argument("--refreshes", type=int, default=0,
                    help="stop --follow after N refreshes (0 = forever; "
                         "mainly for smoke tests)")
    args = ap.parse_args(argv)

    path = args.path
    if os.path.isdir(path):
        metrics_path = os.path.join(path, "metrics.jsonl")
        summary_path = os.path.join(path, "run_summary.json")
        run_dir = path
    elif path.endswith(".jsonl"):
        metrics_path = path
        summary_path = os.path.join(os.path.dirname(path), "run_summary.json")
        run_dir = os.path.dirname(path) or "."
    else:
        metrics_path, summary_path = None, path
        run_dir = os.path.dirname(path) or "."
    trace_path = (os.path.join(os.path.dirname(summary_path),
                               "trace_summary.json")
                  if summary_path else None)
    if not any(p and os.path.exists(p) for p in (metrics_path, summary_path)):
        print(f"metrics_report: nothing to read at {path}", file=sys.stderr)
        return 2
    if not args.follow:
        print(render(metrics_path, summary_path, args.last, trace_path,
                     run_dir))
        return 0

    # --follow: the one-terminal fleet watch.  Re-render from scratch each
    # refresh (the files are small; incremental tailing lives in the
    # aggregator, not the report) with a timestamped banner per frame so
    # scrollback stays legible without cursor tricks.
    import time as _time

    n = 0
    try:
        while True:
            n += 1
            stamp = _time.strftime("%H:%M:%S")
            print(f"\n===== metrics_report --follow  refresh {n} "
                  f"({stamp}; every {args.interval:g}s, Ctrl-C stops) =====")
            print(render(metrics_path, summary_path,
                         args.last or 20, trace_path, run_dir))
            sys.stdout.flush()
            if args.refreshes and n >= args.refreshes:
                return 0
            _time.sleep(max(args.interval, 0.0))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
