#!/usr/bin/env python
"""Perf-contract CLI — the measured-runtime regression ratchet.

Extracts canonical perf facts (step time, MFU/throughput, achieved overlap
per collective class, exposed collective seconds, measured pipeline bubble
fraction) from a measurement source and compares them against the committed
per-topology baseline under
``neuronx_distributed_training_tpu/analysis/perf_baselines/``:

    python bench.py --platform cpu > /tmp/bench.json
    python tools/perf_contract.py --check /tmp/bench.json
    python tools/perf_contract.py --check <run_dir>           # trained run
    python tools/perf_contract.py --update-baselines /tmp/bench.json
    python tools/perf_contract.py --update-baselines /tmp/bench.json \
        --justify "new remat default: +12% step time for -30% HBM"

Accepted sources: a ``bench.py`` JSON line (file or stdout capture), a run
dir (``run_summary.json`` + ``metrics.jsonl`` + ``trace_summary.json``), a
bare ``trace_summary.json``, or a ``.jsonl`` whose last line is a bench
record.  The baseline key defaults to the facts' device identity
(``--key`` overrides).

``--check`` fails (exit 1) on any regression beyond the baseline's noise
bands: step time (PC101), MFU/throughput (PC102), per-class achieved
overlap (PC201), exposed collective seconds naming the collective class
(PC202), measured bubble growth (PC301), measured-vs-predicted bubble
outside the calibration band (PC302), or cost-model residual drift (PC401)
— each explained in subsystem terms (docs/observability.md
"Perf contracts").  A missing baseline is PC000 unless ``--allow-missing``
(the bench smoke's bootstrap mode) downgrades it to a warning.

``--update-baselines`` is the ratchet's write side: improvements commit
silently; a REGRESSION refuses to commit without ``--justify`` (recorded
in-file).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # tools/_jsonout


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("source", nargs="+",
                    help="measurement source(s): bench JSON line file, run "
                         "dir, trace_summary.json, or .jsonl evidence log")
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--check", action="store_true", default=True,
                      help="diff against the committed baseline (default)")
    mode.add_argument("--update-baselines", action="store_true",
                      help="rewrite the committed baseline(s); a regression "
                           "requires --justify")
    ap.add_argument("--key", metavar="NAME",
                    help="baseline key (default: derived from the facts' "
                         "device identity, e.g. cpu_bench)")
    ap.add_argument("--justify", metavar="TEXT",
                    help="in-file justification for a baseline regression "
                         "(--update-baselines)")
    ap.add_argument("--noise", action="append", default=[],
                    metavar="BAND=VALUE",
                    help="noise-band override recorded into the baseline "
                         "on update (repeatable), e.g. --noise "
                         "step_time_frac=1.5 for a CPU smoke whose wall "
                         "clock varies across machines")
    ap.add_argument("--baselines-dir", metavar="DIR",
                    help="baseline directory override (default: the "
                         "committed analysis/perf_baselines/)")
    ap.add_argument("--allow-missing", action="store_true",
                    help="a missing baseline is a warning, not a failure "
                         "(bootstrap mode for fresh topologies)")
    ap.add_argument("--json", metavar="PATH",
                    help="machine-readable report ('-' for stdout, "
                         "guaranteed last line)")
    args = ap.parse_args(argv)

    from neuronx_distributed_training_tpu.analysis import perf_contract as pc
    from neuronx_distributed_training_tpu.analysis.report import AuditReport

    bdir = Path(args.baselines_dir) if args.baselines_dir else None
    noise = {}
    for spec in args.noise:
        band, _, value = spec.partition("=")
        if band not in pc.DEFAULT_NOISE:
            ap.error(f"unknown noise band {band!r}; supported: "
                     f"{sorted(pc.DEFAULT_NOISE)}")
        try:
            noise[band] = float(value)
        except ValueError:
            ap.error(f"--noise {spec!r}: value must be a number")
    failed = False
    out: dict = {"reports": []}
    for source in args.source:
        try:
            facts = pc.load_facts(source)
        except pc.PerfContractError as e:
            rep = AuditReport(config=str(source))
            rep.add("PC000", "error", str(e),
                    hint="point at a bench JSON line, a run dir, or a "
                         "trace_summary.json")
            print(rep.format())
            out["reports"].append(rep.to_dict())
            failed = True
            continue
        key = args.key or pc.default_key(facts)
        if args.update_baselines:
            try:
                path, rep = pc.update_baseline(
                    key, facts, justify=args.justify, baselines_dir=bdir,
                    noise=noise or None)
                drift = rep.by_severity() or "no drift"
                print(f"perf baseline [{key}]: updated -> {path} ({drift})")
            except pc.PerfContractError as e:
                print(f"perf baseline [{key}]: REFUSED: {e}")
                failed = True
                out["reports"].append({"config": key, "verdict": "error",
                                       "refused": str(e)})
                continue
        else:
            rep = pc.check_perf(key, facts, baselines_dir=bdir,
                                noise=noise or None)
            no_baseline = bool(rep.stats.get("no_baseline"))
            print(f"perf contract [{key}]: {pc.verdict_of(rep)}")
            if rep.findings:
                print(rep.format())
            print()
            if no_baseline and args.allow_missing:
                if {f.rule for f in rep.findings} <= {"PC000"}:
                    pass  # bootstrap: nothing but the missing snapshot
                else:
                    failed = True
            else:
                failed |= rep.failed("error")
        rep_dict = rep.to_dict()
        rep_dict["key"] = key
        rep_dict["facts"] = facts
        out["reports"].append(rep_dict)

    if args.json:
        from _jsonout import write_json

        write_json(out, args.json)

    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
