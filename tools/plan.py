#!/usr/bin/env python
"""Launch planner CLI — pick tp/pp/cp/ep/dp, microbatching, remat, and the
pipeline schedule for a config BEFORE spending a chip-hour.

Built on ``neuronx_distributed_training_tpu.autotune`` (docs/autotuning.md):
enumerate the legal plan lattice, rank it with the analytic roofline, then
AOT-lower the top-k candidates SHRUNK (graph-audit structure checks + real
collective census + measured memory) and print the PlanReport.

Usage:

    python tools/plan.py --config examples/conf/hf_llama3_8B_config.yaml \
        --chips 256 --topology v5e --top-k 5
    python tools/plan.py --config cfg.yaml --chips 64 --apply tuned.yaml
    python tools/plan.py --all-examples --check        # CI gate
    python tools/plan.py --config cfg.yaml --json -    # last line = JSON

``--check`` (the verify-flow gate): for every config, the DECLARED
parallelism must appear among the planner's top-3 mesh factorizations for
its chip count — or the YAML must carry an explicit waiver comment
(``# autotune-waiver: <reason>``).  Keeps shipped examples and the cost
model from diverging silently; analytic-only, no lowering.

Exit code 1 when --check fails (or a plan errors).  ``--json`` writes the
full machine-readable report via the shared ``tools/_jsonout.py`` writer:
with ``--json -`` the LAST stdout line is guaranteed parseable JSON.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # tools/_jsonout


def _example_configs() -> list[str]:
    import glob

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return sorted(glob.glob(os.path.join(here, "examples/conf/*.yaml")))


def _declared_chips(path: str) -> int:
    """Chip count a config is written for: ``trainer.devices`` when present,
    else the smallest world its declared degrees admit (dp = ep)."""
    import yaml

    from neuronx_distributed_training_tpu.config import loader as _loader

    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    raw = _loader._resolve_tree(raw, raw)
    devices = int((raw.get("trainer") or {}).get("devices", 0) or 0)
    if devices:
        return devices
    ds = dict(raw.get("distributed_strategy") or {})

    def deg(key):
        try:
            return max(int(ds.get(key) or 1), 1)
        except (TypeError, ValueError):
            return 1

    return (deg("tensor_model_parallel_size")
            * deg("pipeline_model_parallel_size")
            * deg("context_parallel_size")
            * deg("expert_model_parallel_size"))


def _waiver(path: str) -> str | None:
    """The config's ``# autotune-waiver: <reason>`` comment, if any.

    Only a COMMENT whose body starts with the marker counts — an incidental
    mention in a doc string or quoted value must not disable the gate."""
    with open(path) as f:
        for line in f:
            stripped = line.lstrip()
            if not stripped.startswith("#"):
                continue
            body = stripped.lstrip("#").strip()
            if body.startswith("autotune-waiver:"):
                return body.split("autotune-waiver:", 1)[1].strip()
    return None


def check_config(path: str, *, top_meshes: int = 3,
                 slack: float = 1.10) -> dict:
    """--check: the declared parallelism must be among the planner's top-N
    mesh factorizations for this config's chip count, OR within ``slack`` x
    the best plan's predicted step time (a near-tie between factorizations
    is agreement, not divergence), OR carry a waiver comment."""
    from neuronx_distributed_training_tpu.autotune import plan_config

    chips = _declared_chips(path)
    rep = plan_config(path, chips=chips, topology=None, audit=False,
                      top_k=10**9)
    name = os.path.basename(path)
    if rep.error:
        return {"config": name, "chips": chips, "ok": False,
                "reason": rep.error}
    declared = rep.facts.declared_plan_for(chips) if rep.facts else None
    if declared is None:
        return {"config": name, "chips": chips, "ok": False,
                "reason": "declared degrees do not divide the chip count"}
    # rank distinct MESHES by their best plan (remat/mbs/schedule collapse)
    meshes: list[tuple] = []
    best_of_mesh: dict[tuple, float] = {}
    for c in rep.candidates:
        if c.plan.mesh not in best_of_mesh:
            meshes.append(c.plan.mesh)
            best_of_mesh[c.plan.mesh] = c.estimate.step_seconds
    try:
        mesh_rank = meshes.index(declared.mesh) + 1
    except ValueError:
        mesh_rank = None
    best = rep.candidates[0].estimate.step_seconds if rep.candidates else 0.0
    ratio = (best_of_mesh[declared.mesh] / best
             if mesh_rank is not None and best > 0 else None)
    ok = mesh_rank is not None and (mesh_rank <= top_meshes
                                    or (ratio is not None
                                        and ratio <= slack))
    out = {"config": name, "chips": chips, "ok": ok,
           "declared_mesh": dict(zip(("tp", "pp", "cp", "ep", "dp"),
                                     declared.mesh)),
           "mesh_rank": mesh_rank,
           "vs_best": round(ratio, 3) if ratio is not None else None,
           "top_meshes": [dict(zip(("tp", "pp", "cp", "ep", "dp"), m))
                          for m in meshes[:top_meshes]]}
    if not ok:
        waiver = _waiver(path)
        if waiver:
            out["ok"] = True
            out["waiver"] = waiver
        else:
            out["reason"] = (
                f"declared mesh ranks "
                f"{mesh_rank if mesh_rank else 'outside the lattice'} "
                f"(> top-{top_meshes}"
                + (f", {ratio:.2f}x the best plan" if ratio else "")
                + f"); add an '# autotune-waiver: <why>' comment or "
                  f"revisit the config's parallelism"
            )
    return out


def main() -> None:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--config", action="append", default=[],
                    help="YAML config to plan for (repeatable)")
    ap.add_argument("--all-examples", action="store_true",
                    help="plan every examples/conf/*.yaml")
    ap.add_argument("--chips", type=int, default=None,
                    help="chip count to plan for (default: the config's "
                         "trainer.devices, else its declared degrees)")
    ap.add_argument("--topology", default=None,
                    help="ICI/HBM table to price against "
                         "(v4/v5e/v5p/v6e/cpu; default: detect from the "
                         "local device)")
    ap.add_argument("--top-k", type=int, default=5,
                    help="candidates to audit + report (default 5)")
    ap.add_argument("--no-audit", dest="audit", action="store_false",
                    help="analytic ranking only — skip the shrunk AOT "
                         "lowering of the top-k")
    ap.add_argument("--max-mbs", type=int, default=8,
                    help="largest micro_batch_size the lattice explores")
    ap.add_argument("--hbm-headroom", type=float, default=0.9,
                    help="fraction of topology HBM the plan may fill")
    ap.add_argument("--check", action="store_true",
                    help="CI gate: declared parallelism must be in the "
                         "planner's top-3 meshes (or carry an "
                         "'# autotune-waiver:' comment)")
    ap.add_argument("--calibrate-from", metavar="SUMMARY",
                    help="a trace_summary.json (telemetry.trace), a "
                         "memory_summary.json (telemetry.memory), a "
                         "comms_summary.json (tools/comms_bench.py), or a "
                         "run dir holding any of them: price comms with the "
                         "MEASURED per-collective-class overlap, the HBM "
                         "model with MEASURED per-subsystem ratios, and/or "
                         "the interconnect with MEASURED per-axis bandwidth "
                         "instead of the built-in priors "
                         "(docs/observability.md 'Device-time profiling' / "
                         "'Memory observability' / 'Interconnect "
                         "observatory')")
    ap.add_argument("--apply", metavar="OUT_YAML",
                    help="write a copy of the (single) config with the "
                         "winning knobs imposed")
    ap.add_argument("--json", metavar="PATH",
                    help="machine-readable report ('-' for stdout; the "
                         "payload is the guaranteed-last line)")
    ap.add_argument("--platform", default="cpu", choices=["cpu", "tpu"],
                    help="jax platform for the audit lowerings (default "
                         "cpu: planning is static)")
    args = ap.parse_args()

    configs = list(args.config)
    if args.all_examples:
        configs += _example_configs()
    if not configs:
        ap.error("nothing to do: pass --config and/or --all-examples")
    if args.apply and len(configs) != 1:
        ap.error("--apply works on exactly one --config")

    # Size the virtual CPU world BEFORE jax initializes: shrunk audits clamp
    # every degree to 2, so 16 covers tp x pp x cp x ep all active at once.
    if args.platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count=16"
            ).strip()

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from neuronx_distributed_training_tpu.autotune import plan_config

    failed = False
    out: dict = {}

    if args.check:
        results = [check_config(p) for p in configs]
        for r in results:
            mark = "ok" if r["ok"] else "FAIL"
            extra = (f" (waiver: {r['waiver']})" if r.get("waiver")
                     else (f" — {r['reason']}" if not r["ok"] else ""))
            rank = r.get("mesh_rank")
            print(f"[{mark:4s}] {r['config']} chips={r['chips']} "
                  f"mesh_rank={rank}{extra}")
            failed |= not r["ok"]
        n_ok = sum(1 for r in results if r["ok"])
        print(f"plan --check: {n_ok}/{len(results)} configs consistent "
              f"with the planner (top-3 meshes or waived)")
        out["check"] = results
    else:
        out["reports"] = []
        for path in configs:
            rep = plan_config(
                path, chips=args.chips, topology=args.topology,
                top_k=args.top_k, audit=args.audit,
                hbm_headroom=args.hbm_headroom, max_mbs=args.max_mbs,
                max_devices=min(16, len(jax.devices())),
                calibration=args.calibrate_from,
            )
            print(rep.format(top=args.top_k))
            print()
            out["reports"].append(rep.to_dict())
            failed |= rep.error is not None or rep.winner is None
            if args.apply and rep.winner is not None:
                from neuronx_distributed_training_tpu.autotune.planner import (
                    apply_plan,
                )

                apply_plan(path, args.apply, rep.winner.plan, rep.facts)
                print(f"applied winning plan -> {args.apply}")

    if args.json:
        from _jsonout import write_json

        write_json(out, args.json)

    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
