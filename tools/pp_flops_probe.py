"""Pipeline redundant-FLOPs probe (VERDICT r3 item 2).

Question: after hoisting the lm-head/loss out of the wavefront (round-robin
parked outputs, loss once outside the manual region — ``parallel/pipeline.py``)
how close are the pipelined step's compiled FLOPs to the unpipelined step at
equal tokens?  Before the hoist every pipe rank computed head+CE every tick:
~``pp * (nm+pp-1)/nm``x the head FLOPs of the unpipelined step (the reference
instead computes loss on the last stage only, ``base.py:378-381``).

Method: compile the REAL jitted train step on the 8-device virtual CPU mesh
with a vocab-heavy tiny model (vocab 8192 >> hidden 128, so the head term
dominates like Llama-3's 128k-vocab head) at pp=4/dp=2 and pp=1/dp=8, equal
global batch, and compare XLA ``cost_analysis()['flops']``.  The only
remaining expected gap is bubble-tick stage compute ((pp-1)/(nm+pp-1) of
stage FLOPs, inherent to the SPMD wavefront — the reference's MPMD ranks
idle instead, same wall-clock); the embed is hoisted+sharded too.

Measured 2026-07-30 (this probe, bench_results/pp_flops_probe.json):
ratio pp4/pp1 = 1.0205 — within 2.1% of unpipelined at equal tokens.

Run:  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      PYTHONPATH=/root/repo:$PYTHONPATH python tools/pp_flops_probe.py
"""

import json
import os

import jax

jax.config.update("jax_platforms", "cpu")

from neuronx_distributed_training_tpu.config.loader import load_config  # noqa: E402
from neuronx_distributed_training_tpu.trainer.loop import Trainer  # noqa: E402

HIDDEN = 128
LAYERS = 8
SEQ = 256
VOCAB = 8192
GBS = int(os.environ.get("PROBE_GBS", 32))


def cfg_for(pp: int) -> dict:
    return {
        "name": f"flopsprobe_pp{pp}",
        "model_source": "hf",
        "seed": 0,
        "trainer": {"max_steps": 1, "log_every_n_steps": 1},
        "distributed_strategy": {
            "pipeline_model_parallel_size": pp,
            "tensor_model_parallel_size": 1,
        },
        "data": {"global_batch_size": GBS, "micro_batch_size": 1,
                 "seq_length": SEQ, "synthetic": True},
        "model": {
            "vocab_size": VOCAB,
            "hidden_size": HIDDEN,
            "intermediate_size": 2 * HIDDEN,
            "num_layers": LAYERS,
            "num_attention_heads": 4,
            "num_key_value_heads": 4,
            "max_position_embeddings": SEQ,
            "activations_checkpoint_granularity": "full",
            "optim": {"name": "adamw_fp32OptState", "lr": 1e-4,
                      "sched": {"name": "constant"}},
        },
        "precision": {"type": "fp32"},
    }


def measure(pp: int) -> dict:
    t = Trainer.from_config(load_config(cfg_for(pp)), enable_checkpointing=False)
    batch = next(t.data_module.sharded_batches(t.mesh))
    compiled = t.train_step.lower(
        t.params, t.opt_state, batch, jax.random.PRNGKey(0)
    ).compile()
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    out = {"pp": pp, "flops": float(ca.get("flops", -1.0))}
    del t
    return out


def main() -> None:
    res = {pp: measure(pp) for pp in (1, 4)}
    for r in res.values():
        print(json.dumps(r))
    nm = GBS // (8 // 4)  # pp=4 -> dp=2, mbs=1
    # head fwd FLOPs at equal tokens (one pass over the global batch);
    # cost_analysis() reports the per-device partitioned module, so scale
    # the global-batch head FLOPs down by the 8 devices for a coherent ratio
    head_per_device = 2.0 * GBS * SEQ * HIDDEN * VOCAB / 8
    summary = {
        "nm_pp4": nm,
        "flops_ratio_pp4_vs_pp1": round(res[4]["flops"] / res[1]["flops"], 4),
        "head_fwd_fraction_of_pp1": round(head_per_device / res[1]["flops"], 4),
        "old_design_head_redundancy_x": round(4 * (nm + 4 - 1) / nm, 2),
        "pp4_gflops": round(res[4]["flops"] / 1e9, 2),
        "pp1_gflops": round(res[1]["flops"] / 1e9, 2),
    }
    print(json.dumps(summary))
    with open("bench_results/pp_flops_probe.json", "w") as f:
        json.dump({**{f"pp{k}": v for k, v in res.items()},
                   "summary": summary}, f, indent=1)


if __name__ == "__main__":
    main()
