"""Flagship-shape pipeline memory probe (VERDICT r3 item 3).

Round 3 measured the GPipe-wavefront stage-input retention at TOY shape
(h=256, s=512) and extrapolated the 70B-class delta; this probe lowers the
REAL jitted train step (fwd+bwd+AdamW) compile-only at flagship shape —
pp=8 x vp=2, nm=32, mbs=1, s=8192, h=8192, L=80 (Llama-3-70B geometry,
examples/conf/hf_llama3_70B_config.yaml) — on the 8-device virtual CPU mesh
and reads XLA's own ``memory_analysis()``.

Nothing is allocated: params/opt-state/batch are ``jax.eval_shape`` abstract
values, so the 70B argument tensors never materialize; buffer assignment
(the same XLA pass TPU uses) still reports the temp high-water.

The real 70B config runs tp=32 with SP, which shards the [1, s, h] stage
inputs 32x; on the pp-only virtual mesh each rank carries the full 128 MiB
input, so analytic expectations below scale by exactly that factor — the
GPipe-vs-1F1B retention RATIO is shape-preserving.

Run:  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      PYTHONPATH=/root/repo:$PYTHONPATH python tools/pp_memory_flagship.py
"""

import functools
import json
import os
import time

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from neuronx_distributed_training_tpu.models import llama  # noqa: E402
from neuronx_distributed_training_tpu.optim.adamw import (  # noqa: E402
    AdamWConfig,
    init_opt_state,
    opt_state_specs,
)
from neuronx_distributed_training_tpu.optim.lr import (  # noqa: E402
    linear_annealing_with_warmup,
)
from neuronx_distributed_training_tpu.parallel import sharding as shd  # noqa: E402
from neuronx_distributed_training_tpu.parallel.mesh import (  # noqa: E402
    MeshConfig,
    build_mesh,
)
from neuronx_distributed_training_tpu.parallel.pipeline import (  # noqa: E402
    pipeline_loss,
    to_interleaved,
)
from neuronx_distributed_training_tpu.trainer.step import (  # noqa: E402
    jit_train_step,
    make_train_step,
    microbatch_split,
)
from neuronx_distributed_training_tpu.utils.dtypes import DtypePolicy  # noqa: E402

PP = int(os.environ.get("PROBE_PP", 8))
VP = int(os.environ.get("PROBE_VP", 2))
NM = int(os.environ.get("PROBE_NM", 32))
MBS = 1
SEQ = int(os.environ.get("PROBE_SEQ", 8192))
HID = int(os.environ.get("PROBE_HID", 8192))
LAYERS = int(os.environ.get("PROBE_LAYERS", 80))


def main() -> None:
    cfg = llama.LlamaConfig(
        vocab_size=128256,
        hidden_size=HID,
        intermediate_size=28672 * HID // 8192,
        num_layers=LAYERS,
        num_attention_heads=64,
        num_kv_heads=8,
        max_position_embeddings=SEQ,
        attention_impl="flash",
        # the 70B config runs chunked CE (fusions.chunked_ce class) to keep
        # the [*, s, 128k] logits out of HBM; 8 chunks matches its scale
        vocab_chunks=8,
        tie_word_embeddings=True,
        activations_checkpoint_granularity="full",
    )
    policy = DtypePolicy.from_precision_config("mixed_precision")
    mesh = build_mesh(
        MeshConfig(pipeline_model_parallel_size=PP,
                   virtual_pipeline_model_parallel_size=VP),
        devices=jax.devices()[:8],
    )

    embed_fn, stage_fn, stage_loss = llama.pipeline_hooks(cfg, policy)

    def loss_fn(p, batch, step_key):
        mbs = microbatch_split(batch, NM)
        return pipeline_loss(
            p, p["layers"], mbs, embed_fn=embed_fn, stage_fn=stage_fn,
            loss_fn=stage_loss, mesh=mesh, num_microbatches=NM,
            virtual_pipeline_size=VP,
        ), {}

    def init_fn(key):
        p = llama.init_params(key, cfg, policy)
        return {**p, "layers": to_interleaved(p["layers"], PP, VP)}

    pspecs = llama.param_specs(cfg, pipeline=True)
    pspecs["layers"] = jax.tree_util.tree_map(
        lambda s: P(None, s[0], None, *tuple(s)[1:]), pspecs["layers"],
        is_leaf=lambda x: isinstance(x, P),
    )

    with mesh, shd.use_mesh(mesh):
        t0 = time.perf_counter()
        params = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        opt_state = jax.eval_shape(
            functools.partial(init_opt_state, policy=policy), params
        )
        ospecs = opt_state_specs(params, pspecs, mesh, zero1=True, policy=policy)
        step = make_train_step(
            loss_fn, AdamWConfig(grad_clip_norm=1.0),
            linear_annealing_with_warmup(1e-4, 10, 100), policy,
            num_microbatches=1, param_specs=pspecs,
        )
        jstep = jit_train_step(step, mesh, pspecs, ospecs,
                               batch_spec=P(("data", "expert")))
        batch = {
            "input_ids": jax.ShapeDtypeStruct((NM * MBS, SEQ), jnp.int32),
            "labels": jax.ShapeDtypeStruct((NM * MBS, SEQ), jnp.int32),
        }
        lowered = jstep.lower(params, opt_state, batch, jax.random.PRNGKey(1))
        t_lower = time.perf_counter() - t0
        compiled = lowered.compile()
        t_compile = time.perf_counter() - t0 - t_lower
        ma = compiled.memory_analysis()

    gib = 2.0 ** 30
    stage_input = MBS * SEQ * HID * 2  # bf16 [mbs, s, h]
    ticks = NM * VP + PP - 1
    out = {
        "shape": {"pp": PP, "vp": VP, "nm": NM, "mbs": MBS, "seq": SEQ,
                  "hidden": HID, "layers": LAYERS, "vocab": 128256},
        "lower_s": round(t_lower, 1),
        "compile_s": round(t_compile, 1),
        "temp_gib": round(ma.temp_size_in_bytes / gib, 3),
        "argument_gib": round(ma.argument_size_in_bytes / gib, 3),
        "output_gib": round(ma.output_size_in_bytes / gib, 3),
        "analytic": {
            "stage_input_mib": round(stage_input / 2 ** 20, 1),
            "gpipe_ticks": ticks,
            "gpipe_retention_gib": round(ticks * stage_input / gib, 3),
            "onef1b_retention_gib": round(PP * stage_input / gib, 3),
            "parked_plus_embed_feed_gib": round(
                2 * (-(-NM // PP)) * stage_input / gib, 3
            ),
            "note": "real 70B runs tp=32+SP: divide activation terms by 32",
        },
    }
    print(json.dumps(out))
    # canonical artifact only for the canonical shape: PROBE_* override runs
    # write a suffixed file instead of clobbering the headline numbers
    default = (PP, VP, NM, SEQ, HID, LAYERS) == (8, 2, 32, 8192, 8192, 80)
    suffix = "" if default else (
        f"_pp{PP}vp{VP}nm{NM}s{SEQ}h{HID}L{LAYERS}"
    )
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "bench_results",
        f"pp_memory_flagship{suffix}.json",
    )
    with open(path, "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
