"""Pipeline activation-memory high-water probe (VERDICT r2 item 3).

Question: does the GPipe-wavefront schedule (forward for all ``nm``
microbatches, then autodiff reverse) retain O(nm) stage inputs vs 1F1B's
O(pp) — at what cost, per XLA's own buffer assignment?

Method: lower + compile the REAL jitted train step on the 8-device virtual
CPU mesh at pp=4 / nm=16 (pp4 x dp2) and compare ``memory_analysis()``
against (a) the unpipelined step with the identical per-device workload
(dp=2, nm=16 microbatch scan) and (b) the analytic stage-input footprint.
CPU-backend buffer assignment uses the same XLA pass as TPU, so the RATIO
pipeline/unpipelined is meaningful even though absolute bytes differ from a
TPU compile.

Run:  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      PYTHONPATH=/root/repo:$PYTHONPATH python tools/pp_memory_probe.py
"""

import json

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from neuronx_distributed_training_tpu.config.loader import load_config  # noqa: E402
from neuronx_distributed_training_tpu.trainer.loop import Trainer  # noqa: E402

HIDDEN = 256
LAYERS = 8
SEQ = 512
import os
GBS = int(os.environ.get("PROBE_GBS", 32))  # dp=2, mbs=1 -> nm=GBS/2 at pp=4


def cfg_for(pp: int) -> dict:
    return {
        "name": f"memprobe_pp{pp}",
        "model_source": "hf",
        "seed": 0,
        "trainer": {"max_steps": 1, "log_every_n_steps": 1},
        "distributed_strategy": {
            "pipeline_model_parallel_size": pp,
            "tensor_model_parallel_size": 1,
        },
        "data": {"global_batch_size": GBS, "micro_batch_size": 1,
                 "seq_length": SEQ, "synthetic": True},
        "model": {
            "vocab_size": 2048,
            "hidden_size": HIDDEN,
            "intermediate_size": 2 * HIDDEN,
            "num_layers": LAYERS,
            "num_attention_heads": 4,
            "num_key_value_heads": 4,
            "max_position_embeddings": SEQ,
            "activations_checkpoint_granularity": "full",
            "optim": {"name": "adamw_fp32OptState", "lr": 1e-4,
                      "sched": {"name": "constant"}},
        },
        "precision": {"type": "fp32"},
    }


def measure(pp: int) -> dict:
    t = Trainer.from_config(load_config(cfg_for(pp)), enable_checkpointing=False)
    batch = next(t.data_module.sharded_batches(t.mesh))
    lowered = t.train_step.lower(t.params, t.opt_state, batch, jax.random.PRNGKey(0))
    compiled = lowered.compile()
    ma = compiled.memory_analysis()
    out = {
        "pp": pp,
        "temp_bytes": int(ma.temp_size_in_bytes),
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }
    del t
    return out


def main() -> None:
    res = {}
    for pp in (1, 4):
        res[f"pp{pp}"] = measure(pp)
        print(json.dumps(res[f"pp{pp}"]))
    nm = GBS // (8 // res["pp4"]["pp"] // 1 * 1)  # dp = 8/pp
    # analytic per-device stage-input footprint: [nm, mbs, seq, hidden] fp32
    stage_inputs = 16 * 1 * SEQ * HIDDEN * 4
    summary = {
        "nm_pp4": 16,
        "gpipe_stage_input_bytes_analytic": stage_inputs,
        "onef1b_stage_input_bytes_analytic": res["pp4"]["pp"] * 1 * SEQ * HIDDEN * 4,
        "temp_ratio_pp4_vs_pp1": round(
            res["pp4"]["temp_bytes"] / max(res["pp1"]["temp_bytes"], 1), 3),
        "pp4_temp_mb": round(res["pp4"]["temp_bytes"] / 2**20, 2),
        "pp1_temp_mb": round(res["pp1"]["temp_bytes"] / 2**20, 2),
    }
    print(json.dumps(summary))
    with open("bench_results/pp_memory_probe.json", "w") as f:
        json.dump({**res, "summary": summary}, f, indent=1)


if __name__ == "__main__":
    main()
