#!/usr/bin/env python
"""Pre-flight static audit CLI — the gate that runs before a device-hour.

Two layers (docs/static_analysis.md has the rule catalogue):

- **graph audit** (``--config``): AOT-lowers the train step for a YAML config
  on abstract inputs — no TPU, no data files, no arrays — and checks the
  compiled artifact against the config's declared contracts (donation
  aliased, collective census vs parallelism, replication budget, precision).
- **source lint** (``--lint``): the jaxlint AST pass over the package with
  its committed ratchet baseline; NEW findings (and stale baseline entries)
  fail.

Usage:

    python tools/preflight_audit.py --config examples/conf/hf_llama3_8B_config.yaml
    python tools/preflight_audit.py --lint
    python tools/preflight_audit.py --all-examples --lint --json audit.json
    python tools/preflight_audit.py --lint --update-baseline   # rebaseline

Exit code 1 when any finding reaches ``--fail-on`` severity (default
``error``; lint ratchet failures always count).  ``--json`` writes the full
machine-readable report; the terminal always gets the human form.

The graph audit shrinks large configs by default (degrees clamp to 2, dims
to the smallest shapes satisfying them — the *structure* under audit is
preserved); ``--no-shrink`` audits at the config's true size, which needs a
real (or forced-host) device world that large.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # tools/_jsonout


def _required_world(config_paths: list[str], shrink: bool) -> int:
    """Device count the audits need — computed from raw YAML before jax
    initializes, so the CPU world can still be sized via XLA_FLAGS."""
    import yaml

    from neuronx_distributed_training_tpu.config import loader as _loader

    world = 1
    for p in config_paths:
        try:
            with open(p) as f:
                raw = yaml.safe_load(f) or {}
            raw = _loader._resolve_tree(raw, raw)
            ds = dict(raw.get("distributed_strategy") or {})

            def deg(key):
                try:
                    v = int(ds.get(key) or 1)
                except (TypeError, ValueError):
                    v = 1
                return min(v, 2) if shrink else v

            base = (deg("tensor_model_parallel_size")
                    * deg("pipeline_model_parallel_size")
                    * deg("context_parallel_size")
                    * deg("expert_model_parallel_size"))
            world = max(world, base * 2)
        except Exception:  # noqa: BLE001 — sizing is best-effort; audit reports
            continue
    return world


def _audit_worker(args: tuple) -> dict:
    """Graph-audit one config in a worker process (--jobs).  The parent
    exported XLA_FLAGS / JAX_PLATFORMS before the pool spawned, so each
    worker initializes its own correctly-sized CPU world; results carry the
    pre-rendered text so the parent can merge output deterministically."""
    path, shrink, slack, platform, contracts = args
    import jax

    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from neuronx_distributed_training_tpu.analysis.graph_audit import (
        audit_config,
    )

    artifacts: dict = {}
    rep = audit_config(path, shrink=shrink, replication_slack=slack,
                       artifacts_out=artifacts)
    out = {"path": path, "report": rep.to_dict(), "text": rep.format(),
           "failed_warn": rep.failed("warn"),
           "failed_error": rep.failed("error")}
    if contracts and artifacts:
        # the graph-contract ratchet rides the SAME lowering the absolute
        # rules just audited — no second compile per config.  A failure
        # here (corrupt snapshot, fingerprint bug) must become THIS
        # config's finding, not kill the whole sweep.
        try:
            from neuronx_distributed_training_tpu.analysis import (
                graph_contract as gc,
            )

            fp = gc.fingerprint_artifacts(
                artifacts["ctx"], artifacts["compiled"],
                artifacts["stablehlo"], config_name=os.path.basename(path))
            fp["shrunk"] = bool(shrink)
            crep = gc.check_contract(path, fp)
            out["contract"] = crep.to_dict()
            out["contract_text"] = (
                f"contract [{os.path.basename(path)}]: "
                f"{crep.worst() or 'clean'}"
                + ("\n" + crep.format() if crep.findings else ""))
            out["failed_warn"] |= crep.failed("warn")
            out["failed_error"] |= crep.failed("error")
        except Exception as e:  # noqa: BLE001 — a worker must return, not die
            out["contract"] = {"verdict": "error",
                               "error": f"{type(e).__name__}: {e}"}
            out["contract_text"] = (
                f"contract [{os.path.basename(path)}]: ERROR "
                f"({type(e).__name__}: {e})")
            out["failed_warn"] = out["failed_error"] = True
    elif contracts:
        out["contract"] = {"verdict": "error",
                           "skipped": "no artifacts (config failed earlier)"}
        out["contract_text"] = f"contract [{os.path.basename(path)}]: " \
                               f"skipped (audit failed before lowering)"
        out["failed_error"] = True
    return out


def main() -> None:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--config", action="append", default=[],
                    help="YAML config to graph-audit (repeatable)")
    ap.add_argument("--all-examples", action="store_true",
                    help="graph-audit every examples/conf/*.yaml")
    ap.add_argument("--lint", action="store_true",
                    help="run the jaxlint source pass with the ratchet "
                         "baseline")
    ap.add_argument("--contracts", action="store_true",
                    help="also check each config's compiled fingerprint "
                         "against its committed graph contract "
                         "(analysis/contracts/ — reuses the audit's "
                         "lowering; tools/graph_contract.py is the "
                         "standalone ratchet CLI)")
    ap.add_argument("--fail-on", choices=["warn", "error"], default="error",
                    help="severity that fails the run (default: error)")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="graph-audit N configs in parallel processes (the "
                         "sweep is embarrassingly parallel); output order "
                         "stays deterministic (default 1: serial)")
    ap.add_argument("--no-shrink", dest="shrink", action="store_false",
                    help="audit configs at true size (needs a device world "
                         "as large as the config's parallel degrees)")
    ap.add_argument("--replication-slack", type=float, default=8.0,
                    help="GA201 fires above slack x the analytic per-device "
                         "budget (default 8)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the full machine-readable report here "
                         "('-' for stdout)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the jaxlint ratchet baseline from the "
                         "current findings (review the diff!)")
    ap.add_argument("--platform", default="cpu", choices=["cpu", "tpu"],
                    help="jax platform for the abstract lowering (default "
                         "cpu: the audit is static)")
    args = ap.parse_args()

    configs = list(args.config)
    if args.all_examples:
        import glob

        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        configs += sorted(glob.glob(os.path.join(here, "examples/conf/*.yaml")))
    if not configs and not args.lint:
        ap.error("nothing to do: pass --config/--all-examples and/or --lint")
    if args.update_baseline and not args.lint:
        ap.error("--update-baseline only makes sense with --lint (the "
                 "baseline is regenerated from the lint findings)")

    # Size the virtual device world BEFORE jax initializes its backend
    # (parent AND any --jobs worker: the env crosses the spawn).
    if configs and args.platform == "cpu":
        world = max(_required_world(configs, args.shrink), 8)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={world}"
            ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"

    failed = False
    out: dict = {"reports": []}

    work = [(p, args.shrink, args.replication_slack, args.platform,
             args.contracts) for p in configs]
    if args.jobs > 1 and len(work) > 1:
        import multiprocessing as mp
        from concurrent.futures import ProcessPoolExecutor

        with ProcessPoolExecutor(
                max_workers=min(args.jobs, len(work)),
                mp_context=mp.get_context("spawn")) as pool:
            results = list(pool.map(_audit_worker, work))
    else:
        results = [_audit_worker(w) for w in work]

    for res in results:  # input order: deterministic merged output
        print(res["text"])
        if "contract_text" in res:
            print(res["contract_text"])
        print()
        report = res["report"]
        if "contract" in res:
            report = {**report, "contract": res["contract"]}
        out["reports"].append(report)
        failed |= res["failed_warn" if args.fail_on == "warn"
                      else "failed_error"]

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from neuronx_distributed_training_tpu.analysis import jaxlint

    if args.lint:
        full = jaxlint.lint_package()
        if args.update_baseline:
            jaxlint.write_baseline(full)
            print(f"jaxlint: baseline rewritten with {len(full.findings)} "
                  f"finding(s) -> {jaxlint.BASELINE_PATH}")
        fresh, stale = jaxlint.apply_ratchet(full, jaxlint.load_baseline())
        n_base = fresh.stats.get("baselined", 0)
        if not fresh.findings and not stale:
            print(f"jaxlint: clean ({n_base} baselined, 0 new)")
        else:
            print(fresh.format())
            for entry in stale:
                print(f"[ERROR] JL999: stale baseline entry (the finding it "
                      f"grandfathers no longer exists): {entry}")
                print("        fix: remove it from jaxlint_baseline.json "
                      "(or run --update-baseline) — the ratchet only "
                      "shrinks")
            if not args.update_baseline:
                failed = True
        out["jaxlint"] = {
            "new": [f.to_dict() for f in fresh.findings],
            "baselined": n_base,
            "stale_baseline_entries": stale,
        }

    if args.json:
        # shared writer (tools/_jsonout.py): with --json -, the payload is
        # guaranteed to be the single parseable LAST stdout line even when
        # warnings/log lines were emitted along the way
        from _jsonout import write_json

        write_json(out, args.json)

    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
