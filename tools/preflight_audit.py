#!/usr/bin/env python
"""Pre-flight static audit CLI — the gate that runs before a device-hour.

Two layers (docs/static_analysis.md has the rule catalogue):

- **graph audit** (``--config``): AOT-lowers the train step for a YAML config
  on abstract inputs — no TPU, no data files, no arrays — and checks the
  compiled artifact against the config's declared contracts (donation
  aliased, collective census vs parallelism, replication budget, precision).
- **source lint** (``--lint``): the jaxlint AST pass over the package with
  its committed ratchet baseline; NEW findings (and stale baseline entries)
  fail.

Usage:

    python tools/preflight_audit.py --config examples/conf/hf_llama3_8B_config.yaml
    python tools/preflight_audit.py --lint
    python tools/preflight_audit.py --all-examples --lint --json audit.json
    python tools/preflight_audit.py --lint --update-baseline   # rebaseline

Exit code 1 when any finding reaches ``--fail-on`` severity (default
``error``; lint ratchet failures always count).  ``--json`` writes the full
machine-readable report; the terminal always gets the human form.

The graph audit shrinks large configs by default (degrees clamp to 2, dims
to the smallest shapes satisfying them — the *structure* under audit is
preserved); ``--no-shrink`` audits at the config's true size, which needs a
real (or forced-host) device world that large.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # tools/_jsonout


def _required_world(config_paths: list[str], shrink: bool) -> int:
    """Device count the audits need — computed from raw YAML before jax
    initializes, so the CPU world can still be sized via XLA_FLAGS."""
    import yaml

    from neuronx_distributed_training_tpu.config import loader as _loader

    world = 1
    for p in config_paths:
        try:
            with open(p) as f:
                raw = yaml.safe_load(f) or {}
            raw = _loader._resolve_tree(raw, raw)
            ds = dict(raw.get("distributed_strategy") or {})

            def deg(key):
                try:
                    v = int(ds.get(key) or 1)
                except (TypeError, ValueError):
                    v = 1
                return min(v, 2) if shrink else v

            base = (deg("tensor_model_parallel_size")
                    * deg("pipeline_model_parallel_size")
                    * deg("context_parallel_size")
                    * deg("expert_model_parallel_size"))
            world = max(world, base * 2)
        except Exception:  # noqa: BLE001 — sizing is best-effort; audit reports
            continue
    return world


def main() -> None:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--config", action="append", default=[],
                    help="YAML config to graph-audit (repeatable)")
    ap.add_argument("--all-examples", action="store_true",
                    help="graph-audit every examples/conf/*.yaml")
    ap.add_argument("--lint", action="store_true",
                    help="run the jaxlint source pass with the ratchet "
                         "baseline")
    ap.add_argument("--fail-on", choices=["warn", "error"], default="error",
                    help="severity that fails the run (default: error)")
    ap.add_argument("--no-shrink", dest="shrink", action="store_false",
                    help="audit configs at true size (needs a device world "
                         "as large as the config's parallel degrees)")
    ap.add_argument("--replication-slack", type=float, default=8.0,
                    help="GA201 fires above slack x the analytic per-device "
                         "budget (default 8)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the full machine-readable report here "
                         "('-' for stdout)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the jaxlint ratchet baseline from the "
                         "current findings (review the diff!)")
    ap.add_argument("--platform", default="cpu", choices=["cpu", "tpu"],
                    help="jax platform for the abstract lowering (default "
                         "cpu: the audit is static)")
    args = ap.parse_args()

    configs = list(args.config)
    if args.all_examples:
        import glob

        here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        configs += sorted(glob.glob(os.path.join(here, "examples/conf/*.yaml")))
    if not configs and not args.lint:
        ap.error("nothing to do: pass --config/--all-examples and/or --lint")
    if args.update_baseline and not args.lint:
        ap.error("--update-baseline only makes sense with --lint (the "
                 "baseline is regenerated from the lint findings)")

    # Size the virtual device world BEFORE jax initializes its backend.
    if configs and args.platform == "cpu":
        world = max(_required_world(configs, args.shrink), 8)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={world}"
            ).strip()

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from neuronx_distributed_training_tpu.analysis import jaxlint
    from neuronx_distributed_training_tpu.analysis.graph_audit import (
        audit_config,
    )

    failed = False
    out: dict = {"reports": []}

    for path in configs:
        rep = audit_config(
            path, shrink=args.shrink,
            replication_slack=args.replication_slack,
        )
        print(rep.format())
        print()
        out["reports"].append(rep.to_dict())
        failed |= rep.failed(args.fail_on)

    if args.lint:
        full = jaxlint.lint_package()
        if args.update_baseline:
            jaxlint.write_baseline(full)
            print(f"jaxlint: baseline rewritten with {len(full.findings)} "
                  f"finding(s) -> {jaxlint.BASELINE_PATH}")
        fresh, stale = jaxlint.apply_ratchet(full, jaxlint.load_baseline())
        n_base = fresh.stats.get("baselined", 0)
        if not fresh.findings and not stale:
            print(f"jaxlint: clean ({n_base} baselined, 0 new)")
        else:
            print(fresh.format())
            for entry in stale:
                print(f"[ERROR] JL999: stale baseline entry (the finding it "
                      f"grandfathers no longer exists): {entry}")
                print("        fix: remove it from jaxlint_baseline.json "
                      "(or run --update-baseline) — the ratchet only "
                      "shrinks")
            if not args.update_baseline:
                failed = True
        out["jaxlint"] = {
            "new": [f.to_dict() for f in fresh.findings],
            "baselined": n_base,
            "stale_baseline_entries": stale,
        }

    if args.json:
        # shared writer (tools/_jsonout.py): with --json -, the payload is
        # guaranteed to be the single parseable LAST stdout line even when
        # warnings/log lines were emitted along the way
        from _jsonout import write_json

        write_json(out, args.json)

    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
