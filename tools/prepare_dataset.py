#!/usr/bin/env python
"""Offline dataset preparation: raw text -> training-ready data.

The reference trains on externally preprocessed corpora — a pretokenized arrow
dir for the HF path (``hf_data_module.py:15-44``, e.g.
``wikicorpus_llama3_tokenized_8k``) or a Megatron ``.bin``/``.idx`` pair for
the mmap path (built by Megatron's ``preprocess_data``).  This tool produces
both formats so the shipped configs are runnable end-to-end:

    # HF arrow (fixed-length input_ids rows, datasets.save_to_disk):
    python tools/prepare_dataset.py --input corpus.jsonl --tokenizer meta-llama/... \
        --seq-length 8192 --output wikicorpus_tokenized_8k

    # Megatron mmap (.bin/.idx, one doc per record):
    python tools/prepare_dataset.py --input corpus.jsonl --tokenizer ... \
        --format megatron --output my_corpus_text_document

Input: .jsonl/.json with a ``text`` field (configurable), or plain .txt
(one doc per line).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

import numpy as np

# runnable from a checkout without installation
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def iter_docs(path: Path, text_key: str):
    if path.suffix == ".jsonl":
        for line in path.open():
            line = line.strip()
            if line:
                yield json.loads(line)[text_key]
    elif path.suffix == ".json":
        data = json.loads(path.read_text())
        for rec in data if isinstance(data, list) else data["data"]:
            yield rec[text_key]
    else:  # plain text, one doc per line
        for line in path.open():
            if line.strip():
                yield line.rstrip("\n")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--input", required=True, help=".jsonl/.json/.txt corpus")
    ap.add_argument("--tokenizer", required=True,
                    help="HF tokenizer dir or hub name (or 'char' for testing)")
    ap.add_argument("--output", required=True)
    ap.add_argument("--seq-length", type=int, default=8192,
                    help="row length for arrow format (+1 token kept for the "
                         "in-model label shift)")
    ap.add_argument("--format", choices=["arrow", "megatron"], default="arrow")
    ap.add_argument("--text-key", default="text")
    ap.add_argument("--append-eos", action="store_true", default=True)
    args = ap.parse_args()

    if args.tokenizer == "char":
        from neuronx_distributed_training_tpu.data.build import CharTokenizer

        tok = CharTokenizer()
    else:
        from transformers import AutoTokenizer

        tok = AutoTokenizer.from_pretrained(args.tokenizer)
    eos = getattr(tok, "eos_token_id", None)

    docs = []
    for text in iter_docs(Path(args.input), args.text_key):
        ids = tok.encode(text)
        if args.append_eos and eos is not None:
            ids = list(ids) + [eos]
        docs.append(np.asarray(ids, dtype=np.int32))
    if not docs:
        sys.exit("no documents found")
    print(f"tokenized {len(docs)} docs, {sum(len(d) for d in docs):,} tokens")

    if args.format == "megatron":
        from neuronx_distributed_training_tpu.data.megatron.dataset import (
            write_indexed_dataset,
        )

        write_indexed_dataset(args.output, docs)
        print(f"wrote {args.output}.bin/.idx (Megatron mmap)")
        return

    # arrow: concatenate-and-chunk to fixed rows (the load-bearing "all rows
    # same length" rule — one XLA graph for every batch)
    import datasets

    stream = np.concatenate(docs)
    row = args.seq_length
    n_rows = len(stream) // row
    if n_rows == 0:
        sys.exit(f"corpus ({len(stream)} tokens) shorter than one row ({row})")
    rows = stream[: n_rows * row].reshape(n_rows, row)
    ds = datasets.Dataset.from_dict({"input_ids": rows.tolist()})
    ds.save_to_disk(args.output)
    print(f"wrote {args.output}: {n_rows} rows x {row} tokens (arrow)")


if __name__ == "__main__":
    main()
