#!/usr/bin/env python
"""Quantization-readiness report — what compressed collectives would buy.

Reads the tensor-numerics telemetry a run streamed into its artifacts
(``exp_manager.telemetry.tensorstats``) and simulates block-scaled int8
quantization per collective class: predicted SQNR / RMS relative error per
layer-group at configurable block sizes, wire bytes saved, and — when the
run also captured a device trace (``trace_summary.json``) — the measured
exposed seconds each class would claw back.  The decision artifact for
ROADMAP item 2 (int8/block-scaled compressed collectives per EQuARX).

    python tools/quant_readiness.py nxdt_experiments/run/version_0
    python tools/quant_readiness.py run_dir --block-sizes 32,128,1024
    python tools/quant_readiness.py run_dir --config cfg.yaml --chips 64
    python tools/quant_readiness.py run_dir --json -   # last line = JSON

``--config`` joins the planner's per-collective-class byte volumes
(``autotune.cost_model.collective_byte_volumes``) so classes are sized even
without a trace; analysis itself is pure stdlib (the join needs the repo's
model code).  ``--json`` writes through the shared ``tools/_jsonout.py``
writer: with ``--json -`` the LAST stdout line is guaranteed parseable JSON.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # tools/_jsonout


def _fmt(v, nd=3) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        a = abs(v)
        if a != 0 and (a >= 1e6 or a < 1e-3):
            return f"{v:.3e}"
        return f"{v:.{nd}f}"
    return str(v)


def _byte_volumes(config: str, chips: int | None):
    """Planner join — the one part that needs the repo's model code."""
    from neuronx_distributed_training_tpu.autotune.cost_model import (
        collective_byte_volumes,
    )
    from neuronx_distributed_training_tpu.autotune.space import ModelFacts
    from neuronx_distributed_training_tpu.config.loader import load_config

    cfg = load_config(config)
    facts = ModelFacts.from_config(cfg)
    n = chips
    if not n:
        trainer = dict(cfg.get("trainer") or {})
        n = int(trainer.get("devices") or 0)
    if not n:
        d = facts.declared
        n = max(d.tp * d.pp * d.cp * d.ep, 1) if d else 1
    plan = facts.declared_plan_for(n)
    if plan is None:
        raise ValueError(
            f"declared parallelism of {config} does not divide "
            f"{n} chips — pass an explicit --chips"
        )
    return collective_byte_volumes(facts, plan)


def render(report: dict) -> str:
    lines = ["quantization readiness — block-scaled int8 simulation"]
    if report.get("step") is not None:
        lines[0] += f" (tensorstats through step {report['step']})"
    b = report["classes"].get(report["ranking"][0], {}).get("block_size")
    lines.append(f"  ranked by predicted exposed seconds saved at "
                 f"block size {b}; per-block error table below")
    for kind in report["ranking"]:
        e = report["classes"][kind]
        lines.append("")
        head = f"{kind}:"
        if e.get("phase"):
            head += f"  phase={e['phase']}"
        if e.get("bytes_per_step") is not None:
            head += f"  bytes/step={_fmt(float(e['bytes_per_step']), 0)}"
        if e.get("exposed_seconds") is not None:
            head += f"  exposed_s={_fmt(float(e['exposed_seconds']), 6)}"
        if e.get("predicted_seconds_saved") is not None:
            head += f"  saved_s={_fmt(e['predicted_seconds_saved'], 6)}"
            if e.get("savings_source"):
                # measured_wire_rate (telemetry.comms achieved bandwidth)
                # vs static_exposed_fraction — never leave the provenance
                # of a predicted saving unstated
                head += f" ({e['savings_source']})"
        lines.append(head)
        if "pooled" in e:
            for bs, p in e["pooled"].items():
                lines.append(
                    f"    B={bs:>4}  sqnr_db={_fmt(p['sqnr_db'])}  "
                    f"rel_err_rms={_fmt(p['rel_error_rms'], 6)}  "
                    f"bytes_saved={100 * p['bytes_saved_frac']:.1f}%")
            worst = None
            for g, preds in (e.get("per_group") or {}).items():
                p = preds[max(preds, key=int)]
                if p["sqnr_db"] is not None and (
                        worst is None or p["sqnr_db"] < worst[1]):
                    worst = (g, p["sqnr_db"])
            if worst:
                lines.append(f"    worst group: {worst[0]} "
                             f"(sqnr_db={_fmt(worst[1])})")
        elif e.get("note"):
            lines.append(f"    {e['note']}")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("run_dir", help="run directory holding tensorstats "
                                    "telemetry (run_summary.json / "
                                    "tensorstats.jsonl; trace_summary.json "
                                    "joined when present)")
    ap.add_argument("--block-sizes", default="32,128,512",
                    help="comma-separated quantization block sizes "
                         "(default 32,128,512)")
    ap.add_argument("--orig-bytes", type=float, default=4.0,
                    help="uncompressed bytes per element on the wire "
                         "(default 4.0 = fp32 grads)")
    ap.add_argument("--config", default=None,
                    help="training YAML — joins the planner's per-class "
                         "byte volumes (needs the repo importable)")
    ap.add_argument("--chips", type=int, default=None,
                    help="chip count for --config (default: its "
                         "trainer.devices, else the declared degrees)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the full report as JSON ('-' = stdout "
                         "last line, the shared tools/_jsonout contract)")
    args = ap.parse_args(argv)

    from neuronx_distributed_training_tpu.telemetry.quant_readiness import (
        build_report,
        load_run_dir,
    )

    try:
        block_sizes = [int(b) for b in args.block_sizes.split(",") if b]
        inputs = load_run_dir(args.run_dir)
        volumes = (_byte_volumes(args.config, args.chips)
                   if args.config else None)
        report = build_report(
            inputs["tensorstats"], block_sizes=block_sizes,
            byte_volumes=volumes,
            overlap_by_class=inputs["overlap_by_class"],
            comms=inputs.get("comms"),
            orig_bytes_per_elem=args.orig_bytes,
        )
    except (OSError, ValueError, KeyError) as e:
        print(f"quant_readiness: {e}", file=sys.stderr)
        if args.json:
            from _jsonout import write_json

            write_json({"ok": False, "error": str(e)}, args.json)
        return 2
    print(render(report))
    if args.json:
        from _jsonout import write_json

        write_json({"ok": True, **report}, args.json)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
