#!/usr/bin/env python
"""Enqueue operator commands for a RUNNING training job (the fleet control
plane's command channel, docs/observability.md "Fleet control").

Appends one JSON line to ``<run_dir>/control/commands.jsonl``; rank 0 polls
the file at every logging boundary, folds the command into the consensus
control word, and records parse/dedupe/ack as the ``control`` trail in
``run_summary.json`` — so every host acts on the command at the SAME step:

    python tools/run_ctl.py <run_dir> checkpoint_now   # save at next boundary
    python tools/run_ctl.py <run_dir> stop             # graceful fleet stop
                                                       # (emergency save)
    python tools/run_ctl.py <run_dir> dump             # forensic bundle
    python tools/run_ctl.py <run_dir> list             # queue + ack status
    python tools/run_ctl.py <run_dir> stop --json -    # last line = JSON

``<run_dir>`` is the experiment version dir (the one holding
``run_summary.json`` / ``metrics.jsonl``).  Requires
``exp_manager.telemetry.control.enabled: true`` on the run — ``list`` warns
when the trail shows no evidence of a polling run.

Stdlib-only: ``trainer/control.py`` is loaded by file path (the
``tools/fleet_monitor.py`` posture), so this runs on a login node with
nothing installed.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # tools/_jsonout

from _jsonout import write_json  # noqa: E402


def _load_control_module():
    """``trainer/control.py`` by file path — stdlib-only by design, so the
    package (and jax) never has to be importable here."""
    path = (Path(__file__).resolve().parent.parent
            / "neuronx_distributed_training_tpu" / "trainer" / "control.py")
    spec = importlib.util.spec_from_file_location("_nxdt_control", path)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves string annotations through sys.modules[module]:
    # register BEFORE exec or every @dataclass in the file blows up
    sys.modules["_nxdt_control"] = mod
    spec.loader.exec_module(mod)
    return mod


def _read_trail(run_dir: str) -> dict:
    path = os.path.join(run_dir, "run_summary.json")
    try:
        with open(path) as f:
            return dict(json.load(f).get("control") or {})
    except (OSError, ValueError):
        return {}


def _render_list(ctl, run_dir: str) -> dict:
    """Queue + ack status: every enqueued command, joined against the acks
    the run recorded in ``run_summary.json``'s control trail."""
    trail = _read_trail(run_dir)
    acks = {a.get("id"): a for a in trail.get("commands") or []
            if isinstance(a, dict)}
    queued: list[dict] = []
    path = ctl.commands_path(run_dir)
    if path.exists():
        for line in path.read_text().splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                queued.append({"malformed": line[:120]})
                continue
            if isinstance(rec, dict):
                ack = acks.get(rec.get("id"))
                rec["status"] = (ack or {}).get("status", "pending")
                if ack and ack.get("step") is not None:
                    rec["acked_step"] = ack["step"]
                queued.append(rec)
    out = {
        "run_dir": str(run_dir),
        "commands": queued,
        "decisions": trail.get("decisions") or [],
        "polling": bool(trail),
    }
    print(f"run_ctl: {len(queued)} command(s) in {path}")
    for rec in queued:
        if "malformed" in rec:
            print(f"  (malformed line: {rec['malformed']})")
            continue
        step = (f" @ step {rec['acked_step']}" if "acked_step" in rec else "")
        print(f"  {rec.get('id', '?'):<12} {rec.get('command', '?'):<15} "
              f"{rec.get('status')}{step}"
              + (f"  ({rec['note']})" if rec.get("note") else ""))
    for d in (trail.get("decisions") or [])[-5:]:
        conds = ",".join(d.get("conditions") or [])
        print(f"  decision @ step {d.get('step')}: [{conds}] "
              f"{d.get('reason', '')}")
    if not trail:
        print("run_ctl: no control trail in run_summary.json yet — is "
              "exp_manager.telemetry.control.enabled on (and the run "
              "past its first boundary)?", file=sys.stderr)
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", help="experiment version dir (holds "
                                    "run_summary.json / metrics.jsonl)")
    ap.add_argument("command",
                    choices=["stop", "checkpoint_now", "dump", "list"],
                    help="operator command to enqueue (or 'list' to show "
                         "the queue + ack status)")
    ap.add_argument("--note", default=None,
                    help="free-text note recorded with the command (shows "
                         "up in the stop reason / ack trail)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the result as JSON ('-' = stdout, last "
                         "line, the shared tools/_jsonout contract)")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.run_dir):
        print(f"run_ctl: no such run dir {args.run_dir}", file=sys.stderr)
        return 2
    ctl = _load_control_module()

    if args.command == "list":
        out = _render_list(ctl, args.run_dir)
        if args.json:
            write_json(out, args.json)
        return 0

    rec = ctl.append_command(args.run_dir, args.command, note=args.note)
    print(f"run_ctl: enqueued {args.command} (id {rec['id']}) in "
          f"{ctl.commands_path(args.run_dir)} — rank 0 folds it into the "
          f"control word at the next logging boundary")
    out = {"ok": True, "run_dir": str(args.run_dir), **rec}
    if args.json:
        write_json(out, args.json)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
