#!/bin/bash
# Scripted on-chip measurement session for when the tunnelled TPU heals.
# ORDER MATTERS: capture a safe number FIRST (an OOM can wedge the chip for
# hours — round-2 post-mortem), then run diagnostics, then deeper probes.
# Run from /root/repo:  bash tools/tpu_session.sh
set -o pipefail
cd "$(dirname "$0")/.."
export PYTHONPATH="$PWD:${PYTHONPATH:-}"
STAMP=$(date -u +%Y%m%dT%H%M%S)
OUT=bench_results/tpu_session_$STAMP.log
exec > >(tee -a "$OUT") 2>&1

echo "== 1. health probe =="
timeout 180 python -c "
import time, jax, jax.numpy as jnp
t0=time.time(); d=jax.devices()
v=float(jnp.sum(jnp.ones((256,256),jnp.bfloat16) @ jnp.ones((256,256),jnp.bfloat16)))
print('PROBE_OK', d[0].device_kind, round(time.time()-t0,1), 's')" || {
  echo "backend still wedged; aborting session"; exit 1; }

echo "== 2. SAFE bench capture (conservative depth, both regimes) =="
timeout 2400 python bench.py --steps 10 --warmup 3

echo "== 3. EMA donation probe (workaround removal check) =="
timeout 600 python tools/ema_donation_probe.py

echo "== 4. deeper-stack probe (wedge risk accepted AFTER the capture) =="
timeout 2400 python bench.py --steps 10 --warmup 3 --probe-deeper

echo "== 5. re-verify health (leave the chip clean for the driver) =="
timeout 180 python -c "
import jax, jax.numpy as jnp
print('FINAL_OK', float(jnp.sum(jnp.ones((256,256),jnp.bfloat16) @ jnp.ones((256,256),jnp.bfloat16))))"
echo "session complete: $OUT"
