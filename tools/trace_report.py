#!/usr/bin/env python
"""Render a device-time trace summary as a terminal report.

The ocular check on where device time actually went: achieved compute/comms
overlap per collective class (hidden vs exposed wire time), the top-K
device-time op table, and per-step attribution — everything the windowed
``telemetry.trace`` capture wrote to ``trace_summary.json``.

    python tools/trace_report.py nxdt_experiments/hf_llama3_8B/version_0
    python tools/trace_report.py path/to/trace_summary.json
    python tools/trace_report.py path/to/raw_trace_dir   # runs the parser
    python tools/trace_report.py run_dir --json -        # last line = JSON

Accepts a run dir (reads its ``trace_summary.json``), the summary file
itself, or a RAW capture directory / ``*.trace.json(.gz)`` file — raw
inputs go through ``telemetry.trace_analysis`` on the spot (that path
needs the package importable; the summary-rendering path is stdlib-only).
``--json`` writes the full summary through the shared ``tools/_jsonout.py``
single-last-line contract.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))  # tools/_jsonout


def _fmt_s(v: float) -> str:
    """Seconds, scaled for readability."""
    if v >= 1.0:
        return f"{v:.3f} s"
    if v >= 1e-3:
        return f"{v * 1e3:.2f} ms"
    return f"{v * 1e6:.1f} us"


def _table(rows: list[tuple], header: tuple) -> str:
    widths = [max(len(str(r[i])) for r in [header, *rows])
              for i in range(len(header))]

    def fmt_row(r):
        return "  ".join(str(c).ljust(w) if i == 0 else str(c).rjust(w)
                         for i, (c, w) in enumerate(zip(r, widths)))

    sep = "  ".join("-" * w for w in widths)
    return "\n".join([fmt_row(header), sep, *(fmt_row(r) for r in rows)])


def load_summary(path: str, *, top_k: int = 15) -> dict:
    """Summary dict from any accepted input form (see module docstring)."""
    if os.path.isdir(path):
        summary_file = os.path.join(path, "trace_summary.json")
        if os.path.exists(summary_file):
            with open(summary_file) as f:
                return json.load(f)
        # raw capture dir -> parse in place
        return _analyze(path, top_k)
    if path.endswith(".trace.json") or path.endswith(".trace.json.gz"):
        return _analyze(path, top_k)
    with open(path) as f:
        return json.load(f)


def _analyze(path: str, top_k: int) -> dict:
    try:
        from neuronx_distributed_training_tpu.telemetry.trace_analysis import (
            analyze_trace_dir,
        )
    except ImportError as e:
        raise SystemExit(
            f"trace_report: raw-trace input needs the "
            f"neuronx_distributed_training_tpu package importable ({e}); "
            f"point at a trace_summary.json instead"
        )
    return analyze_trace_dir(path, top_k=top_k)


def render(summary: dict, *, top: int = 10) -> str:
    parts: list[str] = []
    window = summary.get("window") or {}
    head = "device-time trace"
    if window:
        head += (f" (steps {window.get('start_step')}.."
                 f"{window.get('start_step', 0) + window.get('num_steps', 0) - 1})")
    devices = summary.get("devices") or []
    parts.append(
        f"{head}: {summary.get('num_op_events', 0)} op events over "
        f"{len(devices)} device lane{'s' if len(devices) != 1 else ''}")

    total = float(summary.get("total_device_seconds") or 0.0)
    comp = float(summary.get("compute_seconds") or 0.0)
    coll = float(summary.get("collective_seconds") or 0.0)
    exposed = float(summary.get("exposed_collective_seconds") or 0.0)
    ov = summary.get("achieved_overlap")
    lines = [
        "",
        f"  total_device_time         {_fmt_s(total)}",
        f"  compute_time              {_fmt_s(comp)}",
        f"  collective_wire_time      {_fmt_s(coll)}",
        f"  exposed_collective_time   {_fmt_s(exposed)}"
        + (f"  ({100 * exposed / total:.1f}% of device time)"
           if total > 0 else ""),
        f"  achieved_overlap          "
        + (f"{100 * float(ov):.1f}% of collective wire time hidden "
           f"under compute" if ov is not None else
           "n/a (no collectives in the window)"),
    ]
    parts.append("\n".join(lines))

    by_class = summary.get("overlap_by_class") or {}
    if by_class:
        rows = [
            (kind, c.get("count", 0), _fmt_s(c.get("wire_seconds", 0.0)),
             _fmt_s(c.get("hidden_seconds", 0.0)),
             _fmt_s(c.get("exposed_seconds", 0.0)),
             f"{100 * c.get('achieved_overlap', 0.0):.1f}%")
            for kind, c in sorted(by_class.items())
        ]
        parts.append("\noverlap by collective class\n" + _table(
            rows, ("class", "n", "wire", "hidden", "exposed", "overlap")))

    top_ops = (summary.get("top_ops") or [])[:top]
    if top_ops:
        rows = [
            (o["op"], o.get("class", "?"), o.get("count", 0),
             _fmt_s(o.get("total_seconds", 0.0)),
             f"{o.get('mean_us', 0.0):.1f}",
             f"{100 * o.get('share', 0.0):.1f}%")
            for o in top_ops
        ]
        parts.append(f"\ntop {len(rows)} ops by device time\n" + _table(
            rows, ("op", "class", "n", "total", "mean_us", "share")))

    pipe = summary.get("pipeline") or {}
    if pipe:
        mb = pipe.get("bubble_fraction_measured")
        pb = pipe.get("bubble_fraction_predicted")
        head = (f"\npipeline timeline ({pipe.get('schedule')} pp="
                f"{pipe.get('pp')} nm={pipe.get('num_microbatches')} "
                f"vp={pipe.get('vp')}, "
                f"{pipe.get('lane_resolution')} lanes)")
        lines = [head]
        if mb is not None:
            lines.append(
                f"  bubble_fraction_measured  {100 * float(mb):.2f}%"
                + (f"  (predicted {100 * float(pb):.2f}%, residual "
                   f"{100 * (float(mb) - float(pb)):+.2f}%)"
                   if pb is not None else ""))
        if pipe.get("straggler_stage"):
            lines.append(
                f"  straggler_stage           {pipe['straggler_stage']} "
                f"({100 * pipe.get('straggler_busy_fraction', 0.0):.1f}% "
                f"busy)")
        stages = pipe.get("stages") or {}
        if stages:
            rows = [
                (lane, s.get("ticks_detected", 0),
                 _fmt_s(s.get("busy_seconds", 0.0)),
                 _fmt_s(s.get("idle_seconds", 0.0)),
                 f"{100 * s.get('busy_fraction', 0.0):.1f}%",
                 _fmt_s(s.get("collective_seconds", 0.0)))
                for lane, s in sorted(stages.items(),
                                      key=lambda kv: kv[1].get("stage", 0))
            ]
            lines.append(_table(rows, ("stage", "ticks", "busy", "idle",
                                       "busy%", "collective")))
        ticks = pipe.get("ticks") or []
        if ticks:
            # one ASCII Gantt row per stage on a SHARED TIME AXIS: each
            # glyph column is a time bucket, not a tick index.  On the
            # work-compacted executor stages detect UNEQUAL tick counts
            # (hops are gated per work kind), so indexing columns by tick
            # would skew the rows against each other — the busy level of
            # each tick lands in the buckets its [start_us, start_us +
            # dur_us) interval actually covers.
            by_stage: dict = {}
            for t in ticks:
                by_stage.setdefault(t.get("stage", 0), []).append(t)
            t_lo = min(t.get("start_us", 0.0) for t in ticks)
            t_hi = max(t.get("start_us", 0.0) + t.get("dur_us", 0.0)
                       for t in ticks)
            max_ticks = max(len(ts) for ts in by_stage.values())
            ncols = min(100, max_ticks)
            note = ""
            if pipe.get("ticks_truncated"):
                note = ", truncated"
            elif ncols < max_ticks:
                # the axis is coarser than the tick stream: several ticks
                # average into each glyph column
                note = f", {max_ticks} ticks/{ncols} buckets"
            lines.append("  tick gantt (busy per time bucket, ' '=idle "
                         f"'#'=full{note})")
            glyphs = " .:-=#"
            span = max(t_hi - t_lo, 1e-9)
            col_us = span / ncols
            for stage, stage_ticks in sorted(by_stage.items()):
                level = [0.0] * ncols
                covered = [0.0] * ncols
                for t in stage_ticks:
                    a = t.get("start_us", 0.0)
                    b = a + t.get("dur_us", 0.0)
                    busy = t.get("busy_fraction", 0.0)
                    c0 = max(int((a - t_lo) / col_us), 0)
                    c1 = min(int((b - t_lo) / col_us) + 1, ncols)
                    for c in range(c0, c1):
                        lo = t_lo + c * col_us
                        ov = max(0.0, min(b, lo + col_us) - max(a, lo))
                        level[c] += busy * ov
                        covered[c] += ov
                bar = "".join(
                    glyphs[min(int(lv / cv * (len(glyphs) - 1) + 0.5),
                               len(glyphs) - 1)] if cv > 0 else " "
                    for lv, cv in zip(level, covered))
                lines.append(f"    stage {stage}  |{bar}|")
        parts.append("\n".join(lines))

    steps = summary.get("steps") or {}
    if steps:
        rows = [
            (f"step {s}", _fmt_s(d.get("device_seconds", 0.0)),
             _fmt_s(d.get("compute_seconds", 0.0)),
             _fmt_s(d.get("collective_seconds", 0.0)))
            for s, d in sorted(steps.items(), key=lambda kv: int(kv[0]))
        ]
        parts.append("\nper-step device-time attribution\n" + _table(
            rows, ("step", "device", "compute", "collective")))

    parts.append(
        "\ncalibrate the launch planner with this measurement:\n"
        "  python tools/plan.py --config <cfg> --calibrate-from "
        "<trace_summary.json>")
    return "\n".join(parts)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="run dir / trace_summary.json / raw trace "
                                 "dir / *.trace.json(.gz)")
    ap.add_argument("--top", type=int, default=10,
                    help="rows in the op table (default 10)")
    ap.add_argument("--json", metavar="PATH",
                    help="machine-readable summary ('-' for stdout; the "
                         "payload is the guaranteed-last line)")
    args = ap.parse_args(argv)

    try:
        summary = load_summary(args.path, top_k=max(args.top, 15))
    except (OSError, ValueError) as e:
        print(f"trace_report: nothing to read at {args.path}: {e}",
              file=sys.stderr)
        return 2
    print(render(summary, top=args.top))
    if args.json:
        from _jsonout import write_json

        write_json(summary, args.json)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
